"""int8 KV-cache quantization (ops.attention.QuantizedPages).

Decode-step KV reads are the dominant non-weight HBM term at serving
shapes (PERF.md roofline); int8 pages + per-token-per-head scales halve
them. These tests pin the write/read roundtrip against the bf16 page
path and the engine-level wiring (config validation, backend forcing,
end-to-end generation).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from opsagent_tpu.ops.attention import (
    QuantizedPages,
    paged_decode_attention,
    paged_prefix_attention,
    quantize_kv_rows,
    write_kv_pages,
)


def _rand_case(rng, B=2, S=12, K=2, D=16, P=4, MaxP=6, num_pages=16):
    q = jnp.asarray(rng.standard_normal((B, S, K * 2, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, S, K, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, S, K, D)), jnp.float32)
    table = np.full((B, MaxP), -1, np.int32)
    used = 0
    for b in range(B):
        for p in range((S + P - 1) // P):
            table[b, p] = used
            used += 1
    return q, k, v, jnp.asarray(table)


def _pages(num_pages, P, K, D, quant):
    if quant:
        return QuantizedPages(
            jnp.zeros((num_pages, P, K, D), jnp.int8),
            jnp.ones((num_pages, P, K), jnp.float32),
        )
    return jnp.zeros((num_pages, P, K, D), jnp.float32)


def test_quantize_kv_rows_roundtrip_error():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((2, 5, 3, 16)), jnp.float32)
    qv, sc = quantize_kv_rows(x)
    assert qv.dtype == jnp.int8 and sc.shape == (2, 5, 3)
    err = np.abs(np.asarray(qv, np.float32) * np.asarray(sc)[..., None] - np.asarray(x))
    # Symmetric absmax int8: error bounded by half a step per row.
    assert (err <= np.asarray(sc)[..., None] / 2 + 1e-6).all()


@pytest.mark.parametrize("reader", ["decode", "prefix"])
def test_quantized_pages_attention_matches_fp(reader):
    """write -> gather-attend through QuantizedPages must match the bf16
    page path to int8-rounding tolerance."""
    rng = np.random.default_rng(1)
    B, S, K, D, P, MaxP, N = 2, 12, 2, 16, 4, 6, 16
    q, k, v, table = _rand_case(rng, B, S, K, D, P, MaxP, N)
    start = jnp.zeros((B,), jnp.int32)
    lens = jnp.full((B,), S, jnp.int32)

    kf, vf = write_kv_pages(
        _pages(N, P, K, D, False), _pages(N, P, K, D, False),
        k, v, table, start, valid_len=lens,
    )
    kq, vq = write_kv_pages(
        _pages(N, P, K, D, True), _pages(N, P, K, D, True),
        k, v, table, start, valid_len=lens,
    )
    assert isinstance(kq, QuantizedPages)
    if reader == "decode":
        q1 = q[:, -1]
        ref = paged_decode_attention(q1, kf, vf, table, lens)
        got = paged_decode_attention(q1, kq, vq, table, lens)
    else:
        ref = paged_prefix_attention(q, kf, vf, table, start, lens)
        got = paged_prefix_attention(q, kq, vq, table, start, lens)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=5e-2, atol=5e-2
    )


def test_quantized_pages_layer_form_and_chunked_writes():
    """The [L, N, P, K(, D)] layer form: chunked writes at an offset land
    in the right layer's region and read back through the decode path."""
    rng = np.random.default_rng(2)
    B, S, K, D, P, MaxP, N, L = 1, 8, 2, 8, 4, 4, 8, 2
    q, k, v, table = _rand_case(rng, B, S, K, D, P, MaxP, N)
    lens = jnp.full((B,), S, jnp.int32)

    def layered(quant):
        if quant:
            return QuantizedPages(
                jnp.zeros((L, N, P, K, D), jnp.int8),
                jnp.ones((L, N, P, K), jnp.float32),
            )
        return jnp.zeros((L, N, P, K, D), jnp.float32)

    for li in range(L):
        kf, vf = layered(False), layered(False)
        kq, vq = layered(True), layered(True)
        # Two chunked writes: [0, S/2) then [S/2, S).
        h = S // 2
        for lo, hi in ((0, h), (h, S)):
            seg_k, seg_v = k[:, lo:hi], v[:, lo:hi]
            st = jnp.full((B,), lo, jnp.int32)
            vl = jnp.full((B,), hi - lo, jnp.int32)
            kf, vf = write_kv_pages(
                kf, vf, seg_k, seg_v, table, st,
                valid_len=vl, layer=jnp.int32(li),
            )
            kq, vq = write_kv_pages(
                kq, vq, seg_k, seg_v, table, st,
                valid_len=vl, layer=jnp.int32(li),
            )
        ref = paged_decode_attention(
            q[:, -1], kf, vf, table, lens, layer=jnp.int32(li)
        )
        got = paged_decode_attention(
            q[:, -1], kq, vq, table, lens, layer=jnp.int32(li)
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=5e-2, atol=5e-2
        )


# -- pallas-dma quantized kernel ---------------------------------------------

def test_pallas_dma_quantized_matches_xla_reader():
    """The manual-DMA kernel fed QuantizedPages (interpret mode) must
    match the XLA gather reader on the same quantized cache — same
    dequantize math, different data path."""
    from opsagent_tpu.ops.paged_attention_pallas import (
        paged_decode_attention_pallas_dma,
    )

    rng = np.random.default_rng(5)
    B, S, K, D, P, MaxP, N = 2, 20, 2, 32, 4, 8, 16
    q, k, v, table = _rand_case(rng, B, S, K, D, P, MaxP, N)
    start = jnp.zeros((B,), jnp.int32)
    lens = jnp.full((B,), S, jnp.int32)
    kq, vq = write_kv_pages(
        _pages(N, P, K, D, True), _pages(N, P, K, D, True),
        k, v, table, start, valid_len=lens,
    )
    q1 = q[:, -1]
    ref = paged_decode_attention(q1, kq, vq, table, lens)
    got = paged_decode_attention_pallas_dma(
        q1, kq, vq, table, lens, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_pallas_dma_quantized_layer_form():
    """Whole-cache [L, N, ...] QuantizedPages with a layer offset through
    the dma kernel (interpret) vs the XLA reader."""
    from opsagent_tpu.ops.paged_attention_pallas import (
        paged_decode_attention_pallas_dma,
    )
    from opsagent_tpu.ops.attention import QuantizedPages

    rng = np.random.default_rng(6)
    B, S, K, D, P, MaxP, N, L = 1, 10, 2, 16, 4, 4, 8, 3
    q, k, v, table = _rand_case(rng, B, S, K, D, P, MaxP, N)
    start = jnp.zeros((B,), jnp.int32)
    lens = jnp.full((B,), S, jnp.int32)
    pages = QuantizedPages(
        jnp.zeros((L, N, P, K, D), jnp.int8),
        jnp.ones((L, N, P, K), jnp.float32),
    )
    kq = write_kv_pages(
        pages, pages, k, v, table, start,
        valid_len=lens, layer=jnp.int32(2),
    )[0]
    q1 = q[:, -1]
    ref = paged_decode_attention(q1, kq, kq, table, lens, layer=jnp.int32(2))
    got = paged_decode_attention_pallas_dma(
        q1, kq, kq, table, lens, interpret=True, layer=jnp.int32(2)
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


@pytest.mark.slow
def test_pallas_dma_quantized_at_bench_8b_decode_shape():
    """Interpret parity at the EXACT pallas-dma-kv bench stage shape
    (B=32, K=8, D=128, P=64, MaxP=12, int8 pages, ragged + one full row)
    — validated before the stage burns chip time, like the bf16 twin in
    test_pallas_paged."""
    from opsagent_tpu.ops.attention import QuantizedPages
    from opsagent_tpu.ops.paged_attention_pallas import (
        paged_decode_attention_pallas_dma,
    )

    rng = np.random.default_rng(43)
    B, K, D, P, MaxP, N = 32, 8, 128, 64, 12, 32 * 12 + 2
    H = 32
    lengths = np.asarray(
        [MaxP * P] + [int(rng.integers(1, MaxP * P + 1)) for _ in range(B - 1)],
        np.int32,
    )
    table = np.full((B, MaxP), -1, np.int32)
    free = list(range(N))
    for b in range(B):
        for i in range(-(-int(lengths[b]) // P)):
            table[b, i] = free.pop()
    # f32 queries: both paths then compute in f32 and must agree tightly
    # (the kernel applies scales in score space, the reader dequantizes —
    # algebraically identical). bf16 rounding-order differences between
    # the two paths are covered by the bf16 twin in test_pallas_paged;
    # THIS test de-risks grid/scratch/indexing at the exact stage shape.
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
    kq = QuantizedPages(
        jnp.asarray(rng.integers(-127, 128, size=(N, P, K, D)), jnp.int8),
        jnp.asarray(rng.uniform(0.01, 0.2, size=(N, P, K)), jnp.float32),
    )
    vq = QuantizedPages(
        jnp.asarray(rng.integers(-127, 128, size=(N, P, K, D)), jnp.int8),
        jnp.asarray(rng.uniform(0.01, 0.2, size=(N, P, K)), jnp.float32),
    )
    tbl = jnp.asarray(table)
    lens = jnp.asarray(lengths)
    ref = paged_decode_attention(q, kq, vq, tbl, lens)
    got = paged_decode_attention_pallas_dma(
        q, kq, vq, tbl, lens, interpret=True
    )
    # atol 1e-3: f32 blockwise online softmax vs the reference's full
    # softmax reorder accumulation over up to 768 tokens; observed worst
    # deviation ~3e-4 on near-zero outputs.
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(ref, np.float32),
        rtol=1e-3, atol=1e-3,
    )


def test_pallas_dma_quantized_under_tp_matches_oracle():
    """QuantizedPages through the tp shard_map wrapper: the scale-plane
    PartitionSpec pytree must mirror the leaf structure and put tp on the
    kv-head axis (one fewer trailing dim than the values)."""
    import jax

    from opsagent_tpu.ops.attention import paged_decode_attention_pallas_tp
    from opsagent_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < 2:
        import pytest

        pytest.skip("needs >= 2 devices")
    mesh = make_mesh(tp=2, dp=1, sp=1, devices=jax.devices()[:2])
    rng = np.random.default_rng(7)
    B, S, K, D, P, MaxP, N = 2, 17, 2, 32, 8, 4, 10
    q, k, v, table = _rand_case(rng, B, S, K, D, P, MaxP, N)
    start = jnp.zeros((B,), jnp.int32)
    lens = jnp.full((B,), S, jnp.int32)
    kq, vq = write_kv_pages(
        _pages(N, P, K, D, True), _pages(N, P, K, D, True),
        k, v, table, start, valid_len=lens,
    )
    q1 = q[:, -1]
    ref = paged_decode_attention(q1, kq, vq, table, lens)
    got = paged_decode_attention_pallas_tp(
        q1, kq, vq, table, lens, mesh, interpret=True, impl="pallas-dma",
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


# -- engine wiring -----------------------------------------------------------

def _engine_kwargs():
    return dict(
        model="tiny-test", max_batch_size=2, num_pages=32, page_size=8,
        max_pages_per_seq=8, prefill_buckets=(16,), decode_block=4,
    )


def test_engine_kv_quantize_generates():
    from opsagent_tpu.serving.engine import Engine, EngineConfig
    from opsagent_tpu.serving.sampler import SamplingParams

    eng = Engine(EngineConfig(kv_quantize="int8", **_engine_kwargs()))
    assert eng.attn_impl == "xla"
    sid = eng.begin_request(
        [5, 6, 7, 8], SamplingParams(max_tokens=6, temperature=0.0)
    )
    while not eng.sequences[sid].done:
        eng.step_block([sid])
    toks = eng.finish(sid)
    assert len(toks) == 6 and all(0 <= t < 512 for t in toks)


def test_engine_kv_quantize_close_to_fp_cache_on_pinned_context():
    """tiny-test at f32: int8 KV rounding must stay near-lossless. The old
    form compared raw greedy tokens — weight-dependent near-ties at the
    argmax flip under rounding, so the expectation was data, not
    correctness. Pinned-logit harness instead: a +100 logit_bias forces
    BOTH engines through the identical token context (so the caches hold
    the same history), and the per-step top-logprob distributions over
    that shared context must agree within a small tolerance — the actual
    near-lossless claim, deterministic on CPU."""
    import numpy as np

    from opsagent_tpu.serving.engine import Engine, EngineConfig
    from opsagent_tpu.serving.sampler import SamplingParams

    prompt = [11, 12, 13, 14, 15]
    pin = 42  # forced continuation token: identical context in both runs
    runs = []
    for kvq in ("", "int8"):
        eng = Engine(EngineConfig(kv_quantize=kvq, **_engine_kwargs()))
        sid = eng.begin_request(
            prompt,
            SamplingParams(
                max_tokens=6, temperature=0.0,
                logit_bias=((pin, 100.0),),
                logprobs=True, top_logprobs=20,
            ),
        )
        while not eng.sequences[sid].done:
            eng.step_block([sid])
        seq = eng.sequences[sid]
        runs.append((eng.finish(sid), list(seq.logprob_data)))
    (toks_fp, lp_fp), (toks_q, lp_q) = runs
    assert toks_fp == [pin] * 6 == toks_q  # bias pinned both contexts
    assert len(lp_fp) == len(lp_q) == 6
    # Steps >= 1 read the quantized pages the pinned context wrote (step 0
    # reads only prefill-written pages — also quantized). Compare the fp
    # run's strongest alternatives against the quantized run's top-20 by
    # token id: every high-mass token must be present with a close
    # logprob. 0.25 nats is far below any argmax-relevant margin while
    # leaving room for int8 rounding at this tiny head dim.
    for step_fp, step_q in zip(lp_fp, lp_q):
        q_by_id = dict(step_q["top"])
        for tid, lp in step_fp["top"][:5]:
            assert tid in q_by_id, f"fp top-5 token {tid} left int8 top-20"
            assert abs(lp - q_by_id[tid]) < 0.25, (
                f"token {tid}: fp {lp} vs int8 {q_by_id[tid]}"
            )


def test_engine_keeps_pallas_dma_with_kv_quantize_at_aligned_head_dim(
    monkeypatch,
):
    """kv_quantize no longer forces xla when the manual-DMA kernel (which
    has a quantized path) is selected AND the head dim satisfies its
    alignment rule."""
    from dataclasses import replace

    from opsagent_tpu.models.config import get_config_preset
    from opsagent_tpu.serving.engine import Engine, EngineConfig

    monkeypatch.setenv("OPSAGENT_PAGED_BACKEND", "pallas-dma")
    cfg128 = replace(get_config_preset("tiny-test"), head_dim=128)
    eng = Engine(
        EngineConfig(kv_quantize="int8", warmup=False, **_engine_kwargs()),
        model_cfg=cfg128,
    )
    assert eng.attn_impl == "pallas-dma"


def test_engine_rejects_bad_kv_quantize_and_mla_combo():
    from opsagent_tpu.serving.engine import Engine, EngineConfig

    with pytest.raises(ValueError, match="kv_quantize"):
        Engine(EngineConfig(kv_quantize="int4", **_engine_kwargs()))
    kwargs = dict(_engine_kwargs(), model="tiny-mla")
    with pytest.raises(ValueError, match="MLA"):
        Engine(EngineConfig(kv_quantize="int8", **kwargs))


def test_engine_kv_quantize_speculative_matches_plain():
    """Speculative decoding over the quantized cache (verify_step writes
    and reads QuantizedPages) must emit exactly the plain quantized
    engine's greedy tokens — speculation is exact for greedy regardless
    of the cache's storage format."""
    from opsagent_tpu.serving.engine import Engine, EngineConfig
    from opsagent_tpu.serving.sampler import SamplingParams

    prompt = [7, 8, 9, 7, 8, 9, 7, 8]  # repetitive: lets drafts engage
    outs = []
    for k in (0, 3):
        eng = Engine(EngineConfig(
            kv_quantize="int8", speculative_k=k, **_engine_kwargs()
        ))
        sid = eng.begin_request(
            prompt, SamplingParams(max_tokens=10, temperature=0.0)
        )
        while not eng.sequences[sid].done:
            eng.step_block([sid])
        outs.append(eng.finish(sid))
    assert outs[0] == outs[1]


def test_engine_kv_quantize_under_tp_mesh():
    """Quantized pages (values AND scales) must shard over tp and execute."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    from opsagent_tpu.serving.engine import Engine, EngineConfig
    from opsagent_tpu.serving.sampler import SamplingParams

    eng = Engine(EngineConfig(
        tp=2, kv_quantize="int8", **_engine_kwargs()
    ))
    sid = eng.begin_request(
        [3, 4, 5], SamplingParams(max_tokens=4, temperature=0.0)
    )
    while not eng.sequences[sid].done:
        eng.step_block([sid])
    assert len(eng.finish(sid)) == 4
