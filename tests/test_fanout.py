"""Cluster-scale audit fan-out (ISSUE 19): plan/scatter/reduce over the
fleet router.

Covers the tentpole's guarantees on the tiny CPU fleet:

- the synthetic cluster is a pure function of (resources, seed,
  issue_fraction) and the deterministic detector recovers every injected
  issue from its probe evidence (recall ground truth is trustworthy);
- the reduce is byte-identical across runs and contains per-child
  failures as ``finding_unavailable`` rows instead of dropping
  resources;
- N concurrent children sharing one system+context prefix re-prefill it
  at most once per replica (priming + prefix trie), on a single replica
  AND on a 2-replica fleet;
- the router's admission gate sheds batch-class work at a LOWER
  watermark than interactive, and the scheduler admits interactive
  ahead of queued batch children within one tick;
- the acceptance run: a >= 200-resource cluster over a 2-replica
  in-process fleet with zero failed children, recall 1.0, >= 90% of
  children avoiding re-prefill, a byte-identical reduce, zero
  post-warmup compiles over the measured audit, and concurrent
  interactive traffic still admitting and completing (slow lane; the
  tier-1 twin runs the same gates at 24 resources).
"""

import threading
import time
from types import SimpleNamespace

import jax.numpy as jnp
import pytest

from opsagent_tpu import obs
from opsagent_tpu.agent.fanout import (
    FanoutConfig,
    SynthCluster,
    detect_findings,
    run_audit,
)
from opsagent_tpu.agent.fanout.synthcluster import (
    ISSUE_SEVERITY,
    severity_rank,
)
from opsagent_tpu.serving.api import ServingStack
from opsagent_tpu.serving.engine import Engine, EngineConfig
from opsagent_tpu.serving.fleet.router import FleetRouter, OverloadError
from opsagent_tpu.serving.scheduler import _admit_rank

# Fan-out child prompts carry the shared system+inventory prefix
# (~280 byte-tokens under tiny-test); the usual 4x64 test geometry tops
# out at 256 tokens/seq, so the fan-out fleet gets 8x64 = 512.
BASE = dict(
    model="tiny-test", dtype=jnp.float32, tp=1, page_size=8,
    num_pages=512, max_pages_per_seq=64, max_batch_size=4,
    prefill_buckets=(32, 64, 128), decode_block=4, seed=0,
    offload=True,
)


def _fleet(n=2, **router_kw):
    router = FleetRouter(sticky=False, **router_kw)
    stacks = []
    for i in range(n):
        stack = ServingStack(Engine(EngineConfig(**BASE)))
        stacks.append(stack)
        router.add_local(stack, f"r{i}")
    return router, stacks


def _close(stacks):
    for s in stacks:
        s.close()


# -- synthetic cluster + ground truth ----------------------------------------
class TestSynthCluster:
    def test_pure_function_of_inputs(self):
        a = SynthCluster(resources=32, seed=7)
        b = SynthCluster(resources=32, seed=7)
        assert a.work_items() == b.work_items()
        assert a.ground_truth() == b.ground_truth()
        assert all(
            a.describe(r) == b.describe(r) for r in a.work_items()
        )
        c = SynthCluster(resources=32, seed=8)
        assert c.work_items() != a.work_items()

    def test_issue_fraction_and_archetype_mix(self):
        c = SynthCluster(resources=40, seed=1, issue_fraction=0.25)
        truth = c.ground_truth()
        assert len(truth) == 10
        # Round-robin assignment: every archetype is represented.
        assert {f["issue"] for f in truth} == set(ISSUE_SEVERITY)
        # Reduce-sorted: severities in rank order.
        ranks = [severity_rank(f["severity"]) for f in truth]
        assert ranks == sorted(ranks)

    def test_detector_recovers_every_injected_issue(self):
        c = SynthCluster(resources=48, seed=3)
        for p in c.pods:
            found = detect_findings(c.describe(p.resource), p.resource)
            issues = {f["issue"] for f in found}
            if p.issue is None:
                assert not found
            else:
                assert p.issue in issues
                for f in found:
                    assert f["resource"] == p.resource
                    assert f["severity"] == ISSUE_SEVERITY[f["issue"]]

    def test_unknown_resource_probe_is_not_found(self):
        c = SynthCluster(resources=4, seed=0)
        assert "NotFound" in c.describe("nowhere/ghost")


# -- reduce semantics on a fake router (no engine) ----------------------------
class _FakeInfo:
    page_size = 4

    def __init__(self, rid):
        self.replica_id = rid


class _FakeRegistry:
    def __init__(self, n):
        self._infos = [_FakeInfo(f"r{i}") for i in range(n)]

    def alive(self, role=None):
        return list(self._infos)


class _FakeRouter:
    """Tokenize = one token per char; complete succeeds unless the
    resource matches ``fail`` (always) or ``shed_once`` (first call)."""

    def __init__(self, n=1, fail=(), shed_once=()):
        self.registry = _FakeRegistry(n)
        self.fail = set(fail)
        self.shed = set(shed_once)
        self.forced = []

    def tokenize(self, body):
        return [
            ord(ch) for m in body["messages"] for ch in m["content"]
        ]

    def complete(self, body, force_replica=None):
        if force_replica is not None:
            self.forced.append(force_replica)
            return {"choices": [{"message": {"content": "{}"}}]}
        user = body["messages"][1]["content"]
        for r in self.fail:
            if r in user:
                raise RuntimeError("child exploded")
        for r in tuple(self.shed):
            if r in user:
                self.shed.discard(r)
                raise OverloadError("fleet overloaded", 1)
        return {"choices": [{"message": {"content": "{}"}}]}


class TestReduce:
    def test_byte_identical_and_full_recall(self):
        cluster = SynthCluster(resources=24, seed=5)
        cfg = FanoutConfig(max_inflight=4, retry_backoff_s=0.0)
        r1 = run_audit(_FakeRouter(n=2), cluster, cfg)
        r2 = run_audit(_FakeRouter(n=2), cluster, cfg)
        assert r1.canonical == r2.canonical
        assert r1.recall(cluster) == 1.0
        assert r1.stats["outcomes"] == {"ok": 24, "shed": 0, "failed": 0}
        assert r1.stats["primes"] == 2
        # Findings arrive reduce-sorted.
        ranks = [severity_rank(f["severity"]) for f in r1.findings]
        assert ranks == sorted(ranks)
        assert r1.report["summary"]["audited"] == 24

    def test_failed_child_contained_as_unavailable_row(self):
        cluster = SynthCluster(resources=12, seed=2)
        victim = cluster.work_items()[3]
        cfg = FanoutConfig(retries=1, retry_backoff_s=0.0)
        rep = run_audit(_FakeRouter(fail=(victim,)), cluster, cfg)
        assert rep.stats["outcomes"]["failed"] == 1
        rows = [
            f for f in rep.findings if f["issue"] == "finding_unavailable"
        ]
        assert len(rows) == 1 and rows[0]["resource"] == victim
        assert rows[0]["severity"] == "unavailable"
        # Every resource is represented: audited + unavailable = planned.
        assert rep.report["summary"]["audited"] == 11
        assert rep.report["summary"]["unavailable"] == 1
        # Same failures -> same bytes (containment is deterministic too).
        rep2 = run_audit(_FakeRouter(fail=(victim,)), cluster, cfg)
        assert rep2.canonical == rep.canonical

    def test_shed_child_retries_and_recovers(self):
        cluster = SynthCluster(resources=8, seed=4)
        victim = cluster.work_items()[0]
        router = _FakeRouter(shed_once=(victim,))
        rep = run_audit(
            router, cluster,
            FanoutConfig(retries=2, retry_backoff_s=0.0),
        )
        assert rep.stats["outcomes"] == {"ok": 8, "shed": 0, "failed": 0}
        assert rep.recall(cluster) == 1.0

    def test_plan_and_reduce_land_in_flight_ledger(self):
        cluster = SynthCluster(resources=6, seed=9)
        rep = run_audit(_FakeRouter(), cluster, FanoutConfig())
        rec = obs.flight.get_recorder()
        plans = [
            e for e in rec.snapshot(kind="fanout_plan")
            if e["fanout_id"] == rep.fanout_id
        ]
        reduces = [
            e for e in rec.snapshot(kind="fanout_reduce")
            if e["fanout_id"] == rep.fanout_id
        ]
        assert len(plans) == 1 and plans[0]["children"] == 6
        assert len(reduces) == 1
        assert reduces[0]["outcomes"]["ok"] == 6

    def test_fanout_metrics_and_history_series(self):
        cluster = SynthCluster(resources=5, seed=6)
        ok0 = obs.FANOUT_CHILDREN.value(outcome="ok")
        run_audit(_FakeRouter(), cluster, FanoutConfig())
        assert obs.FANOUT_CHILDREN.value(outcome="ok") - ok0 == 5
        assert obs.FANOUT_CHILDREN_TOTAL.value() == 5.0
        assert obs.FANOUT_CHILDREN_DONE.value() == 5.0
        assert obs.FANOUT_ACTIVE.value() == 0.0
        h = obs.history.get_history()
        h.sample()
        series = h.query(since=60.0, step=1.0)["series"]
        for name in (
            "fanout.active", "fanout.children_planned",
            "fanout.children_done", "fanout.prefix_hit_rate",
            "fanout.children",
        ):
            assert name in series, name
        assert series["fanout.children_done"]["points"][-1][1] == 5.0


# -- router admission gate: per-class shed watermark --------------------------
class _DepthInfo:
    def __init__(self, depth):
        self._depth = depth

    def queue_depth(self):
        return self._depth


class _DepthRegistry:
    def __init__(self, depths):
        self._infos = [_DepthInfo(d) for d in depths]

    def refresh_local(self):
        pass

    def alive(self, role=None):
        return list(self._infos)


class TestBatchShedWatermark:
    def _router(self, depths, **kw):
        router = FleetRouter(sticky=False, shed_queue_depth=8, **kw)
        router.registry = _DepthRegistry(depths)
        return router

    def test_batch_sheds_at_half_interactive_watermark(self):
        router = self._router([5, 6])
        # Interactive admits: 5 < 8.
        router._check_overload(None, {"slo_class": "interactive"})
        # Batch sheds: 5 >= 8 // 2.
        with pytest.raises(OverloadError) as ei:
            router._check_overload(
                None, {"slo_class": "batch", "fanout_id": "fo-1"},
            )
        assert ei.value.retry_after_s >= 1
        ev = obs.flight.get_recorder().snapshot(kind="request_shed")[-1]
        assert ev["watermark"] == 4
        assert ev["slo_class"] == "batch"
        assert ev["fanout_id"] == "fo-1"

    def test_explicit_batch_watermark_wins(self):
        router = self._router([5, 6], batch_shed_queue_depth=6)
        router._check_overload(None, {"slo_class": "batch"})
        router2 = self._router([6, 7], batch_shed_queue_depth=6)
        with pytest.raises(OverloadError):
            router2._check_overload(None, {"slo_class": "batch"})

    def test_interactive_watermark_unchanged(self):
        router = self._router([8, 9])
        with pytest.raises(OverloadError):
            router._check_overload(None, {"slo_class": "interactive"})


# -- scheduler class fairness -------------------------------------------------
class TestSchedulerFairness:
    def test_admit_rank_orders_classes_stably(self):
        def req(cls, tag):
            r = SimpleNamespace(
                trace=SimpleNamespace(slo_class=cls), tag=tag
            )
            return r

        waiting = [
            req("batch", "b0"), req("background", "g0"), req("batch", "b1"),
            req("interactive", "i0"), req("", "u0"), req("batch", "b2"),
            req("interactive", "i1"),
        ]
        waiting.sort(key=_admit_rank)
        # Interactive (and unclassed-as-interactive) first, background
        # last, arrival order preserved within each class.
        assert [r.tag for r in waiting] == [
            "i0", "u0", "i1", "b0", "b1", "b2", "g0",
        ]

    def test_interactive_admits_before_queued_batch(self):
        """One busy single-slot engine; two batch children queued BEFORE
        an interactive request must not delay it: on slot release the
        class-fair sort admits interactive first."""
        cfg = dict(BASE, max_batch_size=1)
        stack = ServingStack(Engine(EngineConfig(**cfg)))
        router = FleetRouter(sticky=False)
        router.add_local(stack, "r0")
        finished: dict[str, float] = {}
        lock = threading.Lock()

        def submit(name, cls, max_tokens):
            def run():
                router.complete({
                    "messages": [
                        {"role": "user", "content": f"work {name}"},
                    ],
                    "max_tokens": max_tokens, "temperature": 0.0,
                    "slo_class": cls,
                })
                with lock:
                    finished[name] = time.perf_counter()

            t = threading.Thread(target=run, daemon=True)
            t.start()
            return t

        try:
            threads = [submit("hog", "interactive", 48)]
            time.sleep(0.3)  # the hog occupies the only slot
            threads += [
                submit("batch-0", "batch", 8),
                submit("batch-1", "batch", 8),
            ]
            time.sleep(0.3)  # batch children are queued behind the hog
            threads += [submit("inter", "interactive", 8)]
            for t in threads:
                t.join(timeout=180)
            assert set(finished) == {"hog", "batch-0", "batch-1", "inter"}
            assert finished["inter"] < finished["batch-0"]
            assert finished["inter"] < finished["batch-1"]
        finally:
            _close([stack])


# -- shared-prefix admission over real fleets ---------------------------------
class TestSharedPrefixFanout:
    def test_single_replica_children_share_one_prefill(self):
        router, stacks = _fleet(n=1)
        try:
            cluster = SynthCluster(resources=6, seed=0)
            rep = run_audit(
                router, cluster,
                FanoutConfig(max_inflight=4, max_tokens=8),
            )
            n = cluster.resources
            assert rep.stats["outcomes"]["ok"] == n
            assert rep.stats["primes"] == 1
            assert rep.stats["shared_prefix_tokens"] > 0
            # Priming paid the one allowed prefill; all N children hit.
            assert rep.stats["avoided_children"] >= n - 1
            assert rep.stats["prefix_hit_rate"] >= (n - 1) / n
            assert rep.recall(cluster) == 1.0
        finally:
            _close(stacks)

    def test_two_replica_fleet_children_hit_everywhere(self):
        router, stacks = _fleet(n=2)
        try:
            cluster = SynthCluster(resources=8, seed=1)
            rep = run_audit(
                router, cluster,
                FanoutConfig(max_inflight=4, max_tokens=8),
            )
            n = cluster.resources
            assert rep.stats["outcomes"]["ok"] == n
            assert rep.stats["primes"] == 2
            # One prime per replica: whichever replica a child lands on,
            # its shared prefix is already trie-resident.
            assert rep.stats["avoided_children"] >= n - 1
            assert rep.recall(cluster) == 1.0
            assert rep.canonical  # non-empty deterministic bytes
            # fanout_id threads into the router's route decisions.
            decisions = [
                e for e in obs.flight.get_recorder().snapshot(
                    kind="route_decision"
                )
                if e.get("fanout_id") == rep.fanout_id
            ]
            assert len(decisions) >= n
        finally:
            _close(stacks)


# -- acceptance ---------------------------------------------------------------
def _acceptance(resources: int):
    """The ISSUE-19 acceptance scenario at a configurable cluster size."""
    router, stacks = _fleet(n=2)
    try:
        for s in stacks:
            s.engine.warmup("sessions")
        cluster = SynthCluster(resources=resources, seed=0)
        cfg = FanoutConfig(max_inflight=8, max_tokens=8)
        # Pass 1 pins the canonical bytes and absorbs any residual
        # first-shape compiles; one interactive probe warms the
        # streaming path for the same reason.
        rep1 = run_audit(router, cluster, cfg)
        list(router.complete_stream({
            "messages": [{"role": "user", "content": "warm probe"}],
            "max_tokens": 4, "temperature": 0.0, "stream": True,
            "slo_class": "interactive",
        }))
        compiles0 = obs.POST_WARMUP_COMPILES.value()

        ttft_ms: list[float] = []
        shed: list[str] = []
        stop = threading.Event()

        def probe():
            i = 0
            while not stop.is_set():
                i += 1
                t0 = time.perf_counter()
                try:
                    gen = router.complete_stream({
                        "messages": [
                            {"role": "user", "content": f"status {i}"},
                        ],
                        "max_tokens": 4, "temperature": 0.0,
                        "stream": True, "slo_class": "interactive",
                    })
                    next(gen)
                    ttft_ms.append((time.perf_counter() - t0) * 1e3)
                    for _ in gen:
                        pass
                except Exception as e:  # noqa: BLE001
                    shed.append(f"{type(e).__name__}: {e}")
                stop.wait(0.05)

        th = threading.Thread(target=probe, daemon=True)
        th.start()
        rep2 = run_audit(router, cluster, cfg)
        stop.set()
        th.join(timeout=60)

        n = cluster.resources
        # Zero failed children; every resource audited.
        assert rep2.stats["outcomes"] == {"ok": n, "shed": 0, "failed": 0}
        # Recall 1.0 against the injected ground truth.
        assert rep2.recall(cluster) == 1.0
        # >= 90% of children avoided re-prefilling the shared prefix.
        assert rep2.stats["avoided_children"] >= 0.9 * n
        # Byte-identical reduce across the two runs.
        assert rep2.canonical == rep1.canonical
        # Zero post-warmup compiles over the measured audit.
        assert obs.POST_WARMUP_COMPILES.value() - compiles0 == 0
        # Concurrent interactive traffic kept flowing: probes completed,
        # none were shed or errored, and their TTFT stayed sane.
        assert ttft_ms and not shed
        ttft_ms.sort()
        assert ttft_ms[len(ttft_ms) // 2] < 2000.0
    finally:
        _close(stacks)


def test_cluster_audit_acceptance_tier1():
    """Tier-1 twin of the acceptance run (same gates, 24 resources)."""
    _acceptance(24)


def test_cluster_audit_acceptance_200():
    """The full ISSUE-19 acceptance scenario (slow lane)."""
    _acceptance(200)
