"""Flight recorder, compile watchdog, and debug endpoints (ISSUE 3).

Covers the acceptance gates on the tiny CPU engine: (a) the event ring
stays bounded under concurrent writers; (b) an induced anomaly (TTFT
threshold breach on a real engine request) auto-dumps a JSONL file whose
events reconstruct the offending request's dispatch sequence, and
``GET /api/slo`` + the ``slo-check`` CLI report the breach with the same
numbers the PR-1 histograms show; (c) a forced post-warmup recompile
trips the compile watchdog (counter, gauge, anomaly); (d) the
``/api/debug/flight`` and ``/api/slo`` handlers round-trip on both
servers, with the agent server's JWT guard intact.
"""

import asyncio
import glob
import json
import threading

import jax
import jax.numpy as jnp
import pytest
from aiohttp.test_utils import TestClient, TestServer

from opsagent_tpu import obs
from opsagent_tpu.obs.flight import FlightRecorder
from opsagent_tpu.serving.api import ServingStack, build_engine_app
from opsagent_tpu.serving.engine import Engine, EngineConfig
from opsagent_tpu.serving.sampler import SamplingParams
from opsagent_tpu.serving.scheduler import Scheduler

BASE = dict(
    model="tiny-test", dtype=jnp.float32, tp=1, page_size=4,
    num_pages=128, max_pages_per_seq=16, max_batch_size=4,
    prefill_buckets=(8, 16), decode_block=4,
)


def run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


# -- (a) ring bound -----------------------------------------------------------
def test_ring_bound_under_concurrent_writers():
    rec = FlightRecorder(capacity=256, dump_interval_s=1e9)
    n_threads, per_thread = 8, 500

    def writer(tid):
        for i in range(per_thread):
            rec.record("spam", tid=tid, i=i)

    threads = [
        threading.Thread(target=writer, args=(t,)) for t in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    events = rec.snapshot()
    assert len(events) == 256          # bounded, not 4000
    stats = rec.stats()
    assert stats["total_recorded"] == n_threads * per_thread
    assert stats["dropped"] == n_threads * per_thread - 256
    ids = [e["id"] for e in events]
    assert ids == sorted(ids)          # newest-last, no interleaving damage
    # Every event survived intact (no torn writes).
    assert all(e["kind"] == "spam" and "tid" in e for e in events)


def test_snapshot_filters():
    rec = FlightRecorder(capacity=32, dump_interval_s=1e9)
    for i in range(5):
        rec.record("a", i=i)
        rec.record("b", i=i)
    assert len(rec.snapshot(kind="a")) == 5
    assert [e["i"] for e in rec.snapshot(n=3)] == [3, 4, 4]
    assert [e["i"] for e in rec.snapshot(n=2, kind="b")] == [3, 4]


# -- (b) induced anomaly: the acceptance scenario -----------------------------
def test_ttft_breach_dumps_flight_and_slo_agrees(tmp_path, monkeypatch):
    """An induced TTFT-threshold breach on a REAL engine request must (1)
    auto-dump a JSONL whose events reconstruct the request's dispatch
    sequence (admission -> prefill dispatches -> ttft -> anomaly), and
    (2) show up in the SLO evaluation with the same numbers the
    opsagent_ttft_seconds histogram holds."""
    monkeypatch.setenv("OPSAGENT_FLIGHT_DIR", str(tmp_path))
    # Any first token is "late" against a 1 microsecond threshold.
    monkeypatch.setenv("OPSAGENT_SLO_TTFT_MS", "0.001")
    eng = Engine(EngineConfig(**BASE))
    sched = Scheduler(eng)
    sched.start()
    try:
        # A multi-chunk prompt so the dump shows several prefill
        # dispatches for the same sequence.
        toks = sched.complete(
            [257] + list(range(1, 20)), SamplingParams(max_tokens=4),
            timeout_s=120,
        )
        assert toks
    finally:
        sched.stop()

    dumps = sorted(glob.glob(str(tmp_path / "flight-*.jsonl")))
    assert dumps, "TTFT breach produced no flight dump"
    lines = [json.loads(ln) for ln in open(dumps[0])]
    header, events = lines[0], lines[1:]
    assert header["kind"] == "dump_header"
    assert header["reason"] == "ttft_breach"

    ttft_evs = [e for e in events if e["kind"] == "ttft"]
    assert len(ttft_evs) == 1
    sid = ttft_evs[0]["seq_id"]
    # Reconstruction: the admission and every prefill dispatch of the
    # offending sequence precede its ttft event, in recorded order.
    adm = [e for e in events if e["kind"] == "admission" and e["seq_id"] == sid]
    assert len(adm) == 1 and adm[0]["prompt_tokens"] == 20
    prefills = [
        e for e in events
        if e["kind"] == "dispatch" and e.get("op") in (
            "prefill_chunk", "prefill_batch", "mixed"
        ) and (
            e.get("seq_id") == sid
            or sid in (e.get("seq_ids") or [])
            or sid in (e.get("prefill_seq_ids") or [])
        )
    ]
    assert prefills, "no prefill dispatch recorded for the breaching seq"
    # Every prompt token is accounted for across the recorded prefill
    # dispatches (one mixed chunk, or several split-path chunks).
    assert sum(e.get("prefill_tokens", 0) for e in prefills) == 20
    anomaly = [e for e in events if e["kind"] == "anomaly"][-1]
    assert anomaly["reason"] == "ttft_breach" and anomaly["seq_id"] == sid
    assert adm[0]["id"] < prefills[0]["id"] < ttft_evs[0]["id"] < anomaly["id"]
    # The dumped ttft matches what the histogram observed (one sample,
    # so the sum IS the sample).
    assert obs.TTFT_SECONDS.count() == 1
    assert ttft_evs[0]["ttft_ms"] == pytest.approx(
        obs.TTFT_SECONDS.sum() * 1e3, rel=1e-3
    )

    # (2) the SLO watchdog reports the breach from the same histogram.
    from opsagent_tpu.obs.slo import histogram_quantile

    res = obs.slo.evaluate()
    ttft = next(v for v in res["slos"] if v["name"] == "ttft_p50_ms")
    assert ttft["pass"] is False
    assert ttft["count"] == obs.TTFT_SECONDS.count()
    # evaluate() rounds the reported sum/value (6 decimals / 3 decimals
    # of ms) — compare with the matching absolute tolerance, not a
    # relative one that a fast (small-sum) run can undercut.
    assert ttft["sum"] == pytest.approx(obs.TTFT_SECONDS.sum(), abs=5e-7)
    assert ttft["value"] == pytest.approx(
        histogram_quantile(obs.TTFT_SECONDS, 0.5) * 1e3, abs=5e-4
    )
    assert ttft["burn_rate"] > 1.0
    assert res["pass"] is False

    # ...and the slo-check CLI (in-process source) exits 1 on the breach.
    from opsagent_tpu.cli.main import main as cli_main

    assert cli_main(["slo-check"]) == 1


# -- (c) compile watchdog -----------------------------------------------------
def test_forced_post_warmup_recompile_counts_and_dumps(tmp_path, monkeypatch):
    monkeypatch.setenv("OPSAGENT_FLIGHT_DIR", str(tmp_path))
    n_serving0 = obs.COMPILES.value(phase="serving")
    gauge0 = obs.POST_WARMUP_COMPILES.value()
    with obs.flight.warmup_phase():
        jax.jit(lambda x: x * 2 + 11)(jnp.arange(13))
    n_warm = obs.COMPILES.value(phase="warmup")
    assert n_warm >= 1
    assert obs.flight.warmed()
    # Forced recompile AFTER warmup: a fresh program shape.
    jax.jit(lambda x: x * 3 + 17)(jnp.arange(29))
    n_serving = obs.COMPILES.value(phase="serving")
    assert n_serving > n_serving0
    assert obs.POST_WARMUP_COMPILES.value() > gauge0
    # The anomaly dumped the ring, and the ring holds the compile event.
    dumps = glob.glob(str(tmp_path / "flight-*post_warmup_compile*.jsonl"))
    assert dumps
    compiles = obs.flight.get_recorder().snapshot(kind="compile")
    assert any(e["phase"] == "serving" for e in compiles)
    assert any(e["phase"] == "warmup" for e in compiles)
    # The live /metrics gauge form of the zero-post-warmup invariant.
    text = obs.metrics_text()
    assert "opsagent_post_warmup_compiles" in text


def test_compiles_before_any_warmup_are_not_anomalies():
    anomalies0 = len(obs.flight.get_recorder().snapshot(kind="anomaly"))
    jax.jit(lambda x: x + 41)(jnp.arange(5))
    assert obs.COMPILES.value(phase="startup") >= 1
    assert len(
        obs.flight.get_recorder().snapshot(kind="anomaly")
    ) == anomalies0


# -- (d) endpoint round-trips -------------------------------------------------
class _FakeEngine:
    """The endpoints under test never touch the engine; a bare stack
    carrier keeps this test free of a device-engine build."""

    def __init__(self):
        self.cfg = EngineConfig(model="tiny-test")


def _fake_stack():
    s = ServingStack.__new__(ServingStack)
    s.engine = _FakeEngine()
    s.model_name = "tiny-test"
    return s


def test_engine_app_flight_and_slo_roundtrip():
    obs.flight.record("dispatch", op="decode_block", seq_ids=[7])
    obs.flight.record("admission", seq_id=7, prompt_tokens=3,
                      prefix_hit_tokens=0, request_id=None)
    obs.TTFT_SECONDS.observe(0.05)
    app = build_engine_app(_fake_stack())

    async def scenario():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.get("/api/debug/flight")
            assert r.status == 200
            body = await r.json()
            assert body["events"] and body["capacity"] > 0
            kinds = [e["kind"] for e in body["events"]]
            assert "dispatch" in kinds and "admission" in kinds

            r = await client.get("/api/debug/flight?kind=admission&n=1")
            body = await r.json()
            assert [e["kind"] for e in body["events"]] == ["admission"]

            r = await client.get("/api/debug/flight?n=bogus")
            assert r.status == 400

            r = await client.get("/api/slo")
            assert r.status == 200
            slo = await r.json()
            names = {v["name"] for v in slo["slos"]}
            assert {"ttft_p50_ms", "itl_p50_ms", "error_rate"} <= names
            ttft = next(
                v for v in slo["slos"] if v["name"] == "ttft_p50_ms"
            )
            assert ttft["pass"] is True and ttft["count"] == 1

            # Profile capture: not configured -> 403; bad seconds -> 400.
            r = await client.post("/api/debug/profile?seconds=1")
            assert r.status == 403
            r = await client.post("/api/debug/profile?seconds=0")
            assert r.status == 400
        finally:
            await client.close()

    run(scenario())


def test_engine_app_profile_capture_works(tmp_path, monkeypatch):
    monkeypatch.setenv("OPSAGENT_PROFILE_DIR", str(tmp_path))
    app = build_engine_app(_fake_stack())

    async def scenario():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.post("/api/debug/profile?seconds=0.05")
            assert r.status == 200, await r.text()
            body = await r.json()
            assert body["status"] == "captured"
            assert body["logdir"] == str(tmp_path)
        finally:
            await client.close()

    run(scenario())
    # jax wrote an actual trace capture under the logdir.
    assert glob.glob(str(tmp_path / "**" / "*"), recursive=True)


def test_agent_server_slo_public_flight_jwt_guarded():
    from opsagent_tpu.server.app import build_app
    from opsagent_tpu.server.jwtauth import issue_token
    from opsagent_tpu.utils.globalstore import set_global

    set_global("jwtKey", "test-key")
    obs.flight.record("tool_exec", tool="kubectl", outcome="ok",
                      duration_ms=1.0)
    app = build_app()

    async def scenario():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.get("/api/slo")
            assert r.status == 200           # public, like /metrics
            assert "slos" in await r.json()

            r = await client.get("/api/debug/flight")
            assert r.status == 401           # JWT-guarded

            token = issue_token("admin", "test-key")
            r = await client.get(
                "/api/debug/flight",
                headers={"Authorization": f"Bearer {token}"},
            )
            assert r.status == 200
            body = await r.json()
            assert any(
                e["kind"] == "tool_exec" for e in body["events"]
            )
        finally:
            await client.close()

    run(scenario())


# -- flood control (fan-out admission waves) ----------------------------------
class TestFloodControl:
    def test_sampling_keeps_one_in_n_and_counts_suppressed(self):
        rec = FlightRecorder(capacity=64, dump_interval_s=1e9)
        rec.set_sample_rate("admission", 8)
        for i in range(80):
            rec.record("admission", i=i)
        kept = rec.snapshot(kind="admission")
        assert [e["i"] for e in kept] == list(range(0, 80, 8))
        stats = rec.stats()
        assert stats["sampled_out"]["admission"] == 70
        assert stats["sample_rates"]["admission"] == 8
        # Unsampled kinds are untouched.
        rec.record("restart", note="x")
        assert len(rec.snapshot(kind="restart")) == 1

    def test_flood_cannot_wrap_anomaly_context_out_of_the_ring(self):
        """A fan-out's admission wave (10k events) against a 128-slot
        ring: without sampling the wave evicts everything that explains
        the run; with 1-in-256 sampling the earlier context survives."""
        rec = FlightRecorder(capacity=128, dump_interval_s=1e9)
        rec.record("restart", note="the context worth keeping")
        rec.set_sample_rate("admission", 256)
        for i in range(10_000):
            rec.record("admission", i=i)
        assert len(rec.snapshot(kind="admission")) == 40  # ceil(10k/256)
        assert rec.snapshot(kind="restart")  # not evicted
        assert rec.stats()["dropped"] == 0   # ring never even wrapped

    def test_anomaly_opens_a_retention_window(self):
        rec = FlightRecorder(capacity=256, dump_interval_s=1e9)
        rec.anomaly_hold_s = 60.0
        rec.set_sample_rate("dispatch", 8)
        for i in range(16):
            rec.record("dispatch", i=i)      # sampled: 2 kept
        assert len(rec.snapshot(kind="dispatch")) == 2
        rec.anomaly("ttft_breach", request_id="req-1")
        for i in range(16, 26):
            rec.record("dispatch", i=i)      # inside the hold: all kept
        kept = [e["i"] for e in rec.snapshot(kind="dispatch")]
        assert kept == [0, 8] + list(range(16, 26))
        # Window closed -> sampling resumes (white-box: expire the hold).
        rec._retain_until = 0.0
        before = len(rec.snapshot(kind="dispatch"))
        for i in range(26, 42):
            rec.record("dispatch", i=i)
        after = len(rec.snapshot(kind="dispatch"))
        assert after - before == 2

    def test_rate_leq_one_restores_full_recording(self):
        rec = FlightRecorder(capacity=64, dump_interval_s=1e9)
        rec.set_sample_rate("admission", 4)
        for i in range(8):
            rec.record("admission", i=i)
        rec.set_sample_rate("admission", 0)
        for i in range(8, 12):
            rec.record("admission", i=i)
        kept = [e["i"] for e in rec.snapshot(kind="admission")]
        assert kept == [0, 4, 8, 9, 10, 11]
        assert "admission" not in rec.stats()["sample_rates"]

    def test_env_spec_parsed_and_reset_reparses(self, monkeypatch):
        monkeypatch.setenv(
            "OPSAGENT_FLIGHT_SAMPLE", "admission=8, dispatch=16,junk,x=1"
        )
        rec = FlightRecorder(capacity=32, dump_interval_s=1e9)
        assert rec.stats()["sample_rates"] == {
            "admission": 8, "dispatch": 16,
        }
        monkeypatch.setenv("OPSAGENT_FLIGHT_SAMPLE", "ttft=4")
        rec.reset()
        assert rec.stats()["sample_rates"] == {"ttft": 4}
        assert rec.stats()["sampled_out"] == {}
