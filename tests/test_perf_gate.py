"""Perf-regression gate (fast lane): scripts/perf_gate.py /
``opsagent perf-check`` against fixture jsonl pairs — pass,
noise-tolerated wobble, and an injected 20 % regression -> exit 1 —
plus the bench orchestrator's --perf-gate plumbing."""

import json
import subprocess
import sys

import pytest

from opsagent_tpu.cli.perfcheck import (
    DEFAULT_TOLERANCE,
    compare,
    format_report,
    load_rows,
    run_perf_check,
)


def _row(metric, value, unit="tok/s/chip", ttft=None):
    d = {"metric": metric, "value": value, "unit": unit, "extra": {}}
    if ttft is not None:
        d["extra"]["p50_ttft_ms"] = ttft
    return d


BASELINE = [
    _row("paged_decode_throughput[bench-8b,int8,B=32,tpu]", 1899.0,
         ttft=95.3),
    _row("paged_decode_throughput[bench-1b,B=32,tpu]", 4775.2, ttft=117.4),
    # Duplicate metric with a deliberately-slow probe row: best-per-side
    # matching must pick 4775.2, not let 4308.5 mask a regression.
    _row("paged_decode_throughput[bench-1b,B=32,tpu]", 4308.5, ttft=103.4),
    _row("concurrent_sessions[bench-1b,N=32,tpu]", 210.1, ttft=7463.3),
    _row("agent_turn_ttft[bench-1b,tpu]", 180.0, unit="ms"),
]


def _jsonl(path, rows):
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    return str(path)


def test_identical_runs_pass(tmp_path):
    base = _jsonl(tmp_path / "base.jsonl", BASELINE)
    cur = _jsonl(tmp_path / "cur.jsonl", BASELINE)
    assert run_perf_check(cur, baseline=base) == 0


def test_noise_wobble_within_tolerance_passes(tmp_path):
    wobbled = [
        _row("paged_decode_throughput[bench-8b,int8,B=32,tpu]",
             1899.0 * 0.95, ttft=95.3 * 1.1),   # -5 % tok/s, +10 % ttft
        _row("paged_decode_throughput[bench-1b,B=32,tpu]", 4775.2 * 1.04,
             ttft=117.4),
        _row("concurrent_sessions[bench-1b,N=32,tpu]", 210.1 * 0.93,
             ttft=7463.3),
        _row("agent_turn_ttft[bench-1b,tpu]", 180.0 * 1.08, unit="ms"),
    ]
    base = _jsonl(tmp_path / "base.jsonl", BASELINE)
    cur = _jsonl(tmp_path / "cur.jsonl", wobbled)
    assert run_perf_check(cur, baseline=base) == 0


def test_injected_20pct_regression_fails(tmp_path, capsys):
    regressed = [
        _row("paged_decode_throughput[bench-8b,int8,B=32,tpu]",
             1899.0 * 0.80, ttft=95.3),          # the injected regression
        _row("paged_decode_throughput[bench-1b,B=32,tpu]", 4775.2,
             ttft=117.4),
    ]
    base = _jsonl(tmp_path / "base.jsonl", BASELINE)
    cur = _jsonl(tmp_path / "cur.jsonl", regressed)
    assert run_perf_check(cur, baseline=base) == 1
    out = capsys.readouterr().out
    assert "REGRESSION" in out
    assert "bench-8b" in out


def test_lower_better_units_regress_upward(tmp_path):
    base = _jsonl(tmp_path / "base.jsonl", BASELINE)
    cur = _jsonl(tmp_path / "cur.jsonl", [
        _row("agent_turn_ttft[bench-1b,tpu]", 180.0 * 1.5, unit="ms"),
    ])
    assert run_perf_check(cur, baseline=base) == 1
    # ...and a big IMPROVEMENT (latency halved) passes.
    cur2 = _jsonl(tmp_path / "cur2.jsonl", [
        _row("agent_turn_ttft[bench-1b,tpu]", 90.0, unit="ms"),
    ])
    assert run_perf_check(cur2, baseline=base) == 0


def test_ttft_subseries_gates(tmp_path):
    """extra.p50_ttft_ms rides as its own lower-better comparison with
    the looser TTFT tolerance (25 %)."""
    base = _jsonl(tmp_path / "base.jsonl", BASELINE)
    cur = _jsonl(tmp_path / "cur.jsonl", [
        _row("concurrent_sessions[bench-1b,N=32,tpu]", 210.1,
             ttft=7463.3 * 1.5),  # TTFT +50 % at unchanged tok/s
    ])
    assert run_perf_check(cur, baseline=base) == 1


def test_disjoint_metrics_exit_2(tmp_path):
    base = _jsonl(tmp_path / "base.jsonl", BASELINE)
    cur = _jsonl(tmp_path / "cur.jsonl", [
        _row("paged_decode_throughput[tiny-test,B=4,cpu]", 33.0),
    ])
    assert run_perf_check(cur, baseline=base) == 2
    assert run_perf_check(str(tmp_path / "missing.jsonl"), baseline=base) == 2


def test_per_metric_tolerance_overrides(tmp_path):
    base = _jsonl(tmp_path / "base.jsonl", BASELINE)
    cur = _jsonl(tmp_path / "cur.jsonl", [
        _row("concurrent_sessions[bench-1b,N=32,tpu]", 210.1 * 0.7,
             ttft=7463.3),        # -30 %
    ])
    tol = tmp_path / "tol.json"
    tol.write_text(json.dumps({"concurrent_sessions": 0.4}))
    assert run_perf_check(cur, baseline=base,
                          tolerances_file=str(tol)) == 0
    assert run_perf_check(cur, baseline=base) == 1  # default 10 %: fails


def test_best_row_per_side_defeats_probe_masking():
    """The slow cold-restart probe row must not fake a regression for
    the 1B metric, and a current run whose best row regressed must fail
    even if it ALSO contains a slow extra row."""
    cur = [
        _row("paged_decode_throughput[bench-1b,B=32,tpu]", 4700.0),
        _row("paged_decode_throughput[bench-1b,B=32,tpu]", 1000.0),
    ]
    rep = compare(cur, BASELINE)
    v = next(
        x for x in rep["verdicts"]
        if x["metric"] == "paged_decode_throughput[bench-1b,B=32,tpu]"
    )
    assert v["status"] == "ok"
    assert v["baseline"] == 4775.2  # best, not the probe's 4308.5
    assert rep["pass"] is True


def test_compare_report_format():
    rep = compare(BASELINE, BASELINE)
    text = format_report(rep)
    assert "PASS" in text
    assert f"{DEFAULT_TOLERANCE:.0%}" in text


def test_scripts_perf_gate_shim(tmp_path):
    """The CI entrypoint: scripts/perf_gate.py runs jax-free and returns
    the gate's exit code."""
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    base = _jsonl(tmp_path / "base.jsonl", BASELINE)
    cur = _jsonl(tmp_path / "cur.jsonl", [
        _row("paged_decode_throughput[bench-8b,int8,B=32,tpu]",
             1899.0 * 0.8),
    ])
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "perf_gate.py"),
         cur, "--baseline", base],
        capture_output=True, text=True, env=env, timeout=60,
    )
    assert r.returncode == 1, r.stdout + r.stderr
    assert "REGRESSION" in r.stdout


def test_committed_baseline_is_loadable():
    """The default baseline (newest BENCH_r*_local.jsonl) parses into
    comparable series — the gate's real-world input."""
    from opsagent_tpu.cli.perfcheck import default_baseline

    path = default_baseline()
    assert path is not None
    rows = load_rows(path)
    assert rows, "committed baseline has no result lines"
    rep = compare(rows, rows)
    assert rep["pass"] is True and rep["compared"] > 0


def test_bench_perf_gate_flag(monkeypatch):
    """bench.py --perf-gate mirrors --slo-strict: env/argv toggles, exit
    4 on a confirmed regression, no exit when nothing is comparable."""
    import bench

    monkeypatch.setattr(sys, "argv", ["bench.py"])
    monkeypatch.delenv("OPSAGENT_BENCH_PERF_GATE", raising=False)
    assert not bench.perf_gate_enabled()
    monkeypatch.setenv("OPSAGENT_BENCH_PERF_GATE", "1")
    assert bench.perf_gate_enabled()
    monkeypatch.delenv("OPSAGENT_BENCH_PERF_GATE")
    monkeypatch.setattr(sys, "argv", ["bench.py", "--perf-gate"])
    assert bench.perf_gate_enabled()

    # Gate off: never exits, even on a catastrophic row.
    monkeypatch.setattr(sys, "argv", ["bench.py"])
    bench.exit_if_perf_regression([
        _row("paged_decode_throughput[bench-1b,B=32,tpu]", 1.0)
    ])

    # Gate on + regression vs the committed baseline: exit 4.
    monkeypatch.setenv("OPSAGENT_BENCH_PERF_GATE", "1")
    with pytest.raises(SystemExit) as e:
        bench.exit_if_perf_regression([
            _row("paged_decode_throughput[bench-1b,B=32,tpu]", 1.0), None,
        ])
    assert e.value.code == 4

    # Gate on + disjoint metrics (cpu fallback run): passes with a note.
    bench.exit_if_perf_regression([
        _row("paged_decode_throughput[tiny-test,B=4,cpu]", 33.0)
    ])


def test_ragged_sweep_rows_gate_higher_better(tmp_path):
    """The ragged-sweep cells report tok/s/chip and must gate in the
    higher-is-better direction: a dropped kernel-cell value fails, a
    faster one passes, and a brand-new cell (no baseline twin) never
    gates."""
    from opsagent_tpu.cli.perfcheck import _higher_better

    assert _higher_better("tok/s/chip") is True
    cell = ("mixed_ragged_throughput[bench-8b,int8,kv-int8,pallas-dma,"
            "B=32,tpu]")
    base = _jsonl(tmp_path / "base.jsonl", BASELINE + [_row(cell, 2400.0)])
    slower = _jsonl(tmp_path / "cur.jsonl", [_row(cell, 2400.0 * 0.7)])
    assert run_perf_check(slower, baseline=base) == 1
    faster = _jsonl(tmp_path / "cur2.jsonl", [_row(cell, 2400.0 * 1.3)])
    assert run_perf_check(faster, baseline=base) == 0
    fresh = _jsonl(tmp_path / "cur3.jsonl", [
        _row("mixed_ragged_throughput[bench-8b,int4,kv-int8,pallas-dma,"
             "B=32,tpu]", 2800.0),
        _row("paged_decode_throughput[bench-8b,int8,B=32,tpu]", 1899.0),
    ])
    assert run_perf_check(fresh, baseline=base) == 0


def test_weight_stream_sweep_rows_gate_higher_better(tmp_path):
    """The weight-stream prefetch cells (`,ws-pallas-dma,` in the
    metric) are tok/s/chip rows like every other sweep cell: a prefetch
    kernel that loses its overlap must fail the gate, a faster one must
    pass, and the first run of a brand-new ws cell (no baseline twin)
    must not gate at all."""
    ws_cell = ("mixed_ragged_throughput[bench-8b,int8,kv-bf16,xla,"
               "ws-pallas-dma,B=32,tpu]")
    base = _jsonl(
        tmp_path / "base.jsonl", BASELINE + [_row(ws_cell, 3000.0)]
    )
    slower = _jsonl(tmp_path / "cur.jsonl", [_row(ws_cell, 3000.0 * 0.7)])
    assert run_perf_check(slower, baseline=base) == 1
    faster = _jsonl(tmp_path / "cur2.jsonl", [_row(ws_cell, 3000.0 * 1.2)])
    assert run_perf_check(faster, baseline=base) == 0
    # int4 ws cell has no baseline twin yet: reported, never gated.
    fresh = _jsonl(tmp_path / "cur3.jsonl", [
        _row("mixed_ragged_throughput[bench-8b,int4,kv-bf16,xla,"
             "ws-pallas-dma,B=32,tpu]", 3300.0),
        _row(ws_cell, 3000.0),
    ])
    assert run_perf_check(fresh, baseline=base) == 0


def test_audit_fanout_units_gate_in_the_right_direction(tmp_path):
    """audit_latency_s is lower-better (a slower audit regresses);
    prefix_hit_rate is higher-better (children re-prefilling the shared
    prefix regresses)."""
    base = _jsonl(tmp_path / "base.jsonl", [
        _row("audit_fanout[tiny,N=64,R=2,cpu]", 10.0,
             unit="audit_latency_s"),
        _row("audit_fanout_prefix_hit[tiny,N=64,R=2,cpu]", 1.0,
             unit="prefix_hit_rate"),
    ])
    # Latency up 50 % -> regression even though the value "went up".
    cur = _jsonl(tmp_path / "slow.jsonl", [
        _row("audit_fanout[tiny,N=64,R=2,cpu]", 15.0,
             unit="audit_latency_s"),
        _row("audit_fanout_prefix_hit[tiny,N=64,R=2,cpu]", 1.0,
             unit="prefix_hit_rate"),
    ])
    assert run_perf_check(cur, baseline=base) == 1
    # Hit rate collapsing -> regression even though latency held.
    cur2 = _jsonl(tmp_path / "cold.jsonl", [
        _row("audit_fanout[tiny,N=64,R=2,cpu]", 10.0,
             unit="audit_latency_s"),
        _row("audit_fanout_prefix_hit[tiny,N=64,R=2,cpu]", 0.4,
             unit="prefix_hit_rate"),
    ])
    assert run_perf_check(cur2, baseline=base) == 1
    # Both healthy (small wobble) -> pass.
    cur3 = _jsonl(tmp_path / "ok.jsonl", [
        _row("audit_fanout[tiny,N=64,R=2,cpu]", 9.5,
             unit="audit_latency_s"),
        _row("audit_fanout_prefix_hit[tiny,N=64,R=2,cpu]", 0.98,
             unit="prefix_hit_rate"),
    ])
    assert run_perf_check(cur3, baseline=base) == 0
