"""Slice-restart tolerance (SURVEY §5): when the engine fails persistently
(restarted TPU slice, wedged device runtime), the scheduler rebuilds the
engine from config and re-admits every in-flight request from retained
prompts + tokens generated so far — queued work survives, clients see a
completed response, not an error. (The reference's only recovery at this
layer is k8s probe-driven pod restart, which drops all in-flight work.)"""

import time

import jax.numpy as jnp
import numpy as np
import pytest

from opsagent_tpu.serving.engine import Engine, EngineConfig
from opsagent_tpu.serving.sampler import SamplingParams
from opsagent_tpu.serving.scheduler import Request, Scheduler

CFG = dict(
    model="tiny-test", dtype=jnp.float32, tp=1, page_size=8,
    num_pages=256, max_pages_per_seq=32, max_batch_size=4,
    prefill_buckets=(16,),
)


def _sabotage(engine):
    """Make every decode step raise, as a dead device runtime would."""
    def boom(*a, **k):
        raise RuntimeError("device runtime lost")
    engine.step_block = boom


def test_restart_recovers_inflight_request():
    eng = Engine(EngineConfig(**CFG))
    sched = Scheduler(
        eng, engine_factory=lambda: Engine(EngineConfig(**CFG)),
    )
    sched.start()
    try:
        req = Request([1, 2, 3, 4], SamplingParams(max_tokens=8))
        sched.submit(req)
        # Let it admit and decode at least one block, then kill the engine.
        deadline = time.time() + 30
        while time.time() < deadline and not sched._running:
            time.sleep(0.01)
        assert sched._running, "request never started decoding"
        _sabotage(sched.engine)
        assert req.done.wait(120), "request never completed after restart"
        assert not req.error, req.error
        assert sched._restarts == 1
        assert 1 <= len(req.tokens) <= 8
        assert req.finish_reason in ("stop", "length")
    finally:
        sched.stop()


def test_restart_streams_no_duplicate_tokens():
    eng = Engine(EngineConfig(**CFG))
    sched = Scheduler(
        eng, engine_factory=lambda: Engine(EngineConfig(**CFG)),
    )
    sched.start()
    try:
        streamed: list[int] = []
        req = Request(
            [5, 6, 7], SamplingParams(max_tokens=6),
            on_token=streamed.append,
        )
        sched.submit(req)
        deadline = time.time() + 30
        while time.time() < deadline and not sched._running:
            time.sleep(0.01)
        _sabotage(sched.engine)
        assert req.done.wait(120)
        assert not req.error, req.error
        # Streaming delivered exactly the final token list, no replays.
        assert streamed == req.tokens
    finally:
        sched.stop()


def test_restart_budget_exhausted_fails_requests():
    """With no restarts left, persistent failure fails in-flight requests
    (the pre-existing behavior) instead of looping forever."""
    eng = Engine(EngineConfig(**CFG))
    sched = Scheduler(
        eng,
        engine_factory=lambda: Engine(EngineConfig(**CFG)),
        max_restarts=0,
    )
    sched.start()
    try:
        req = Request([1, 2, 3], SamplingParams(max_tokens=4))
        sched.submit(req)
        deadline = time.time() + 30
        while time.time() < deadline and not sched._running:
            time.sleep(0.01)
        _sabotage(sched.engine)
        assert req.done.wait(60)
        assert "engine step failed" in req.error
        assert sched._restarts == 0
    finally:
        sched.stop()


def test_restart_preserves_greedy_continuation():
    """Greedy decoding through a restart must equal uninterrupted greedy
    decoding: the salvaged tokens fold into the re-prefill prompt, so the
    model conditions on exactly the same context."""
    want = Engine(EngineConfig(**CFG)).generate(
        [[9, 8, 7, 6]], SamplingParams(max_tokens=6)
    )[0]

    eng = Engine(EngineConfig(**CFG))
    sched = Scheduler(
        eng, engine_factory=lambda: Engine(EngineConfig(**CFG)),
    )
    sched.start()
    try:
        req = Request([9, 8, 7, 6], SamplingParams(max_tokens=6))
        sched.submit(req)
        deadline = time.time() + 30
        # Wait for at least one generated token so the salvage path runs.
        while time.time() < deadline:
            sids = list(sched._running)
            if sids and sched.engine.sequences[sids[0]].tokens:
                break
            time.sleep(0.01)
        _sabotage(sched.engine)
        assert req.done.wait(120)
        assert not req.error, req.error
        assert req.tokens == want, (req.tokens, want)
    finally:
        sched.stop()
