"""OpenAI logit_bias and presence/frequency penalties: one additive
per-token logit bias applied before sampling (engine hosted-row path)."""

import jax.numpy as jnp
import pytest

from opsagent_tpu.serving.api import ServingStack
from opsagent_tpu.serving.engine import Engine, EngineConfig
from opsagent_tpu.serving.sampler import SamplingParams

KW = dict(
    model="tiny-test", dtype=jnp.float32, tp=1, page_size=8,
    num_pages=256, max_pages_per_seq=32, max_batch_size=4,
    prefill_buckets=(16,),
)


@pytest.fixture(scope="module")
def engine():
    return Engine(EngineConfig(**KW))


def test_negative_bias_forbids_greedy_choice(engine):
    prompt = [257, 3, 4, 5]
    free = engine.generate(
        [prompt], SamplingParams(temperature=0.0, max_tokens=1)
    )[0][0]
    out = engine.generate(
        [prompt],
        SamplingParams(
            temperature=0.0, max_tokens=1,
            logit_bias=((free, -100.0),),
        ),
    )[0]
    assert out[0] != free


def test_positive_bias_forces_token(engine):
    target = 123
    out = engine.generate(
        [[257, 1, 2]],
        SamplingParams(
            temperature=0.0, max_tokens=3,
            logit_bias=((target, 100.0),),
        ),
    )[0]
    assert all(t == target for t in out)


def test_frequency_penalty_breaks_repetition(engine):
    # Unpenalized greedy on a tiny random model settles into a cycle;
    # a strong frequency penalty must produce more distinct tokens.
    base = engine.generate(
        [[257, 6, 6, 6]], SamplingParams(temperature=0.0, max_tokens=16)
    )[0]
    pen = engine.generate(
        [[257, 6, 6, 6]],
        SamplingParams(
            temperature=0.0, max_tokens=16, frequency_penalty=2.0,
        ),
    )[0]
    assert len(set(pen)) >= len(set(base))


def test_api_parses_and_validates():
    stack = ServingStack(Engine(EngineConfig(**KW)))
    try:
        from opsagent_tpu.serving.scheduler import RequestError

        resp = stack.chat_completion({
            "messages": [{"role": "user", "content": "x"}],
            "max_tokens": 2, "temperature": 0,
            "logit_bias": {"42": 5}, "presence_penalty": 0.5,
        })
        assert resp["usage"]["completion_tokens"] == 2

        with pytest.raises(RequestError):
            stack.chat_completion({
                "messages": [{"role": "user", "content": "x"}],
                "logit_bias": {"42": 101},
            })
        with pytest.raises(RequestError):
            stack.chat_completion({
                "messages": [{"role": "user", "content": "x"}],
                "presence_penalty": 3.0,
            })
    finally:
        stack.close()


def test_biased_row_composes_with_plain_batch(engine):
    want = engine.generate(
        [[257, 9, 8, 7]], SamplingParams(temperature=0.0, max_tokens=5)
    )[0]
    a = engine.add_request(
        [257, 9, 8, 7], SamplingParams(temperature=0.0, max_tokens=5)
    )
    b = engine.add_request(
        [257, 2, 3],
        SamplingParams(
            temperature=0.0, max_tokens=5, logit_bias=((50, 100.0),),
        ),
    )
    pending = {a, b}
    while pending:
        engine.step_block(sorted(pending))
        pending = {i for i in pending if not engine.sequences[i].done}
    ta, tb = engine.finish(a), engine.finish(b)
    assert ta == want       # plain row unaffected by the biased neighbor
    assert all(t == 50 for t in tb)
