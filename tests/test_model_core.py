"""Serving-engine core tests (CPU, f32, tiny model): attention ops, paged KV,
prefill/decode equivalence, tensor-parallel sharding equivalence."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from opsagent_tpu.models.config import TINY_TEST
from opsagent_tpu.models import llama
from opsagent_tpu.ops.attention import (
    causal_prefill_attention,
    paged_decode_attention,
    write_kv_pages,
)
from opsagent_tpu.parallel.mesh import make_mesh, shard_params, spec_tree_shardings

CFG = TINY_TEST
DTYPE = jnp.float32


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.PRNGKey(0), dtype=DTYPE)


def naive_attention(q, k, v, lengths=None):
    """Straightforward GQA reference: repeat kv heads, causal mask."""
    B, S, H, D = q.shape
    K = k.shape[2]
    k = jnp.repeat(k, H // K, axis=2)
    v = jnp.repeat(v, H // K, axis=2)
    scores = jnp.einsum("bshd,bthd->bhst", q, k) / (D ** 0.5)
    mask = jnp.tril(jnp.ones((S, S), bool))
    if lengths is not None:
        mask = mask[None, None] & (jnp.arange(S)[None, :] < lengths[:, None])[:, None, None, :]
    else:
        mask = mask[None, None]
    scores = jnp.where(mask, scores, -1e30)
    return jnp.einsum("bhst,bthd->bshd", jax.nn.softmax(scores, -1), v)


def test_causal_attention_matches_naive():
    key = jax.random.PRNGKey(1)
    B, S, H, K, D = 2, 10, 4, 2, 8
    q, k, v = (
        jax.random.normal(kk, (B, S, h, D))
        for kk, h in zip(jax.random.split(key, 3), (H, K, K))
    )
    lengths = jnp.array([10, 7])
    got = causal_prefill_attention(q, k, v, lengths)
    want = naive_attention(q, k, v, lengths)
    np.testing.assert_allclose(got[0], want[0], atol=1e-5)
    np.testing.assert_allclose(got[1, :7], want[1, :7], atol=1e-5)


def test_write_and_paged_decode_matches_contiguous():
    key = jax.random.PRNGKey(2)
    N, P, K, D, H = 8, 4, 2, 8, 4
    B = 2
    lens = [9, 5]
    k_pages = jnp.zeros((N, P, K, D))
    v_pages = jnp.zeros((N, P, K, D))
    # seq0 gets pages [3, 0, 5], seq1 gets [1, 6]
    table = jnp.array([[3, 0, 5, -1], [1, 6, -1, -1]], jnp.int32)
    kf = jax.random.normal(key, (B, 12, K, D))
    vf = jax.random.normal(jax.random.PRNGKey(3), (B, 12, K, D))
    k_pages, v_pages = write_kv_pages(
        k_pages, v_pages, kf, vf, table, jnp.zeros((B,), jnp.int32),
        valid_len=jnp.array(lens),
    )
    q = jax.random.normal(jax.random.PRNGKey(4), (B, H, D))
    got = paged_decode_attention(q, k_pages, v_pages, table, jnp.array(lens))
    for b, ln in enumerate(lens):
        kr = jnp.repeat(kf[b, :ln], H // K, axis=1)  # [ln, H, D]
        vr = jnp.repeat(vf[b, :ln], H // K, axis=1)
        scores = jnp.einsum("hd,thd->ht", q[b], kr) / (D ** 0.5)
        probs = jax.nn.softmax(scores, axis=-1)
        want = jnp.einsum("ht,thd->hd", probs, vr)
        np.testing.assert_allclose(got[b], want, atol=1e-5)


def test_write_kv_pages_drops_invalid():
    N, P, K, D = 2, 2, 1, 2
    k_pages = jnp.zeros((N, P, K, D))
    v_pages = jnp.zeros((N, P, K, D))
    table = jnp.array([[0, -1]], jnp.int32)
    k_new = jnp.ones((1, 4, K, D))
    k2, v2 = write_kv_pages(
        k_pages, v_pages, k_new, k_new, table, jnp.zeros((1,), jnp.int32),
        valid_len=jnp.array([2]),
    )
    # Only the first page's 2 slots were written.
    assert float(k2[0].sum()) == 2 * K * D
    assert float(k2[1].sum()) == 0.0


def test_prefill_matches_forward_full(params):
    B, S = 2, 12
    tokens = jax.random.randint(jax.random.PRNGKey(5), (B, S), 0, CFG.vocab_size)
    lengths = jnp.array([12, 8])
    cache = llama.make_cache(CFG, num_pages=16, page_size=4, dtype=DTYPE)
    table = jnp.array(
        [[0, 1, 2, -1, -1], [3, 4, -1, -1, -1]], jnp.int32
    )
    logits, cache = llama.prefill(params, CFG, tokens, lengths, cache, table, dtype=DTYPE)
    full = llama.forward_full(params, CFG, tokens, dtype=DTYPE)
    np.testing.assert_allclose(logits[0], full[0, 11], rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(logits[1], full[1, 7], rtol=2e-4, atol=2e-4)


def test_decode_chain_matches_forward_full(params):
    """Prefill a prompt, then teacher-force decode steps; every step's logits
    must match the all-at-once causal forward."""
    S_total, S_prompt = 10, 4
    tokens = jax.random.randint(jax.random.PRNGKey(6), (1, S_total), 0, CFG.vocab_size)
    full = llama.forward_full(params, CFG, tokens, dtype=DTYPE)  # [1, S, V]

    cache = llama.make_cache(CFG, num_pages=8, page_size=4, dtype=DTYPE)
    table = jnp.array([[2, 5, 7]], jnp.int32)
    lengths = jnp.array([S_prompt])
    logits, cache = llama.prefill(
        params, CFG, tokens[:, :S_prompt], lengths, cache, table, dtype=DTYPE
    )
    np.testing.assert_allclose(logits[0], full[0, S_prompt - 1], rtol=2e-4, atol=2e-4)

    for t in range(S_prompt, S_total):
        logits, cache = llama.decode_step(
            params,
            CFG,
            tokens[:, t],
            jnp.array([t]),
            cache,
            table,
            active=jnp.array([True]),
            dtype=DTYPE,
        )
        np.testing.assert_allclose(
            logits[0], full[0, t], rtol=3e-4, atol=3e-4,
            err_msg=f"decode step at position {t}",
        )


def test_inactive_slot_does_not_corrupt(params):
    """A padded decode slot (active=False) must not write to pages."""
    cache = llama.make_cache(CFG, num_pages=4, page_size=4, dtype=DTYPE)
    table = jnp.array([[0, -1], [1, -1]], jnp.int32)
    tokens = jnp.array([3, 7])
    logits, cache2 = llama.decode_step(
        params, CFG, tokens, jnp.array([0, 0]), cache, table,
        active=jnp.array([True, False]), dtype=DTYPE,
    )
    # Page 1 (the inactive slot's page) stays zero.
    assert float(jnp.abs(cache2["k"][:, 1]).sum()) == 0.0
    assert float(jnp.abs(cache2["k"][:, 0]).sum()) > 0.0


def test_tp_sharded_prefill_matches_single_device(params):
    """dp=4 x tp=2 over the virtual CPU mesh must be numerically equivalent
    (tiny-test has 2 kv heads, so tp=2 is the max clean kv shard)."""
    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"
    mesh = make_mesh(tp=2, dp=4)
    specs = llama.param_specs(CFG)
    sharded = shard_params(params, specs, mesh)
    cache = llama.make_cache(CFG, num_pages=8, page_size=4, dtype=DTYPE)
    cache_sharded = shard_params(cache, llama.cache_specs(CFG), mesh)
    tokens = jax.random.randint(jax.random.PRNGKey(7), (2, 8), 0, CFG.vocab_size)
    lengths = jnp.array([8, 6])
    table = jnp.array([[0, 1, -1], [2, 3, -1]], jnp.int32)

    ref_logits, _ = llama.prefill(params, CFG, tokens, lengths, cache, table, dtype=DTYPE)

    @jax.jit
    def run(p, c):
        return llama.prefill(p, CFG, tokens, lengths, c, table, dtype=DTYPE)

    with mesh:
        tp_logits, tp_cache = run(sharded, cache_sharded)
    np.testing.assert_allclose(np.asarray(tp_logits), np.asarray(ref_logits), rtol=2e-3, atol=2e-3)


def test_qwen_style_attn_bias():
    from opsagent_tpu.models.config import ModelConfig

    cfg = ModelConfig(
        name="tiny-qwen", vocab_size=128, hidden_size=32, intermediate_size=64,
        num_layers=2, num_heads=4, num_kv_heads=2, attn_bias=True,
        rope_theta=10000.0,
    )
    p = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=DTYPE)
    assert "bq" in p["layers"]
    tokens = jnp.zeros((1, 4), jnp.int32)
    logits = llama.forward_full(p, cfg, tokens, dtype=DTYPE)
    assert logits.shape == (1, 4, 128)
    assert bool(jnp.isfinite(logits).all())


def test_qwen3_qk_norm_tp_sharded_matches_single_device():
    """Qwen3-style per-head q/k RMSNorm (explicit head_dim != hidden/heads)
    under tp=2: the replicated [head_dim] norm weights compose with
    tp-sharded heads, and the sharded prefill must match single-device.
    (HF numeric correctness is pinned separately by the tiny-qwen3-hf
    golden fixture.)"""
    from dataclasses import replace

    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"
    cfg = replace(CFG, qk_norm=True, head_dim=32)
    p = llama.init_params(cfg, jax.random.PRNGKey(3), dtype=DTYPE)
    assert "qn" in p["layers"] and p["layers"]["qn"].shape[-1] == 32
    mesh = make_mesh(tp=2, dp=4)
    sharded = shard_params(p, llama.param_specs(cfg), mesh)
    cache = llama.make_cache(cfg, num_pages=8, page_size=4, dtype=DTYPE)
    cache_sharded = shard_params(cache, llama.cache_specs(cfg), mesh)
    tokens = jax.random.randint(
        jax.random.PRNGKey(8), (2, 8), 0, cfg.vocab_size
    )
    lengths = jnp.array([8, 6])
    table = jnp.array([[0, 1, -1], [2, 3, -1]], jnp.int32)

    ref_logits, _ = llama.prefill(
        p, cfg, tokens, lengths, cache, table, dtype=DTYPE
    )

    @jax.jit
    def run(pp, c):
        return llama.prefill(
            pp, cfg, tokens, lengths, c, table, dtype=DTYPE
        )

    with mesh:
        tp_logits, _ = run(sharded, cache_sharded)
    np.testing.assert_allclose(
        np.asarray(tp_logits), np.asarray(ref_logits), rtol=2e-3, atol=2e-3
    )
