"""Serving stack tests: scheduler continuous batching, OpenAI frontend,
SSE streaming, tool_calls parsing, and the end-to-end agent-over-tpu://
slice with zero external API calls."""

import asyncio
import json
import os
import threading

import jax.numpy as jnp
import pytest
from aiohttp.test_utils import TestClient, TestServer

from opsagent_tpu.serving.api import (
    ServingStack,
    build_engine_app,
    install_stack,
    _stacks,
)
from opsagent_tpu.serving.engine import Engine, EngineConfig
from opsagent_tpu.serving.sampler import SamplingParams
from opsagent_tpu.serving.scheduler import Scheduler, Request


@pytest.fixture(scope="module")
def stack():
    cfg = EngineConfig(
        model="tiny-test",
        dtype=jnp.float32,
        tp=1,
        page_size=4,
        num_pages=128,
        max_pages_per_seq=16,
        max_batch_size=4,
        prefill_buckets=(32, 64),
        max_new_tokens_default=8,
    )
    s = ServingStack(Engine(cfg))
    install_stack("tiny-test", s)
    yield s
    s.close()
    _stacks.pop("tiny-test", None)


def test_scheduler_many_concurrent(stack):
    """16 concurrent sessions through a batch-4 engine all complete."""
    results = {}
    errors = []

    def worker(i):
        try:
            toks = stack.scheduler.complete(
                [257, i % 200 + 1, 2, 3], SamplingParams(max_tokens=4),
                timeout_s=300,
            )
            results[i] = toks
        except Exception as e:  # noqa: BLE001
            errors.append((i, repr(e)))

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    assert not errors, errors
    assert len(results) == 16
    assert all(1 <= len(v) <= 4 for v in results.values())


def test_chat_completion_shape(stack):
    resp = stack.chat_completion(
        {
            "model": "tiny-test",
            "messages": [
                {"role": "system", "content": "sys"},
                {"role": "user", "content": "hello"},
            ],
            "max_tokens": 4,
        }
    )
    assert resp["object"] == "chat.completion"
    choice = resp["choices"][0]
    assert choice["message"]["role"] == "assistant"
    assert choice["finish_reason"] in ("stop", "length")
    assert resp["usage"]["prompt_tokens"] > 0
    assert 1 <= resp["usage"]["completion_tokens"] <= 4


def test_tool_calls_parsing(stack):
    text = json.dumps(
        {
            "tool_calls": [
                {
                    "id": "call_9",
                    "type": "function",
                    "function": {
                        "name": "kubectl",
                        "arguments": "{\"command\": \"get ns\"}",
                    },
                }
            ]
        }
    )
    calls = stack._parse_tool_calls(text)
    assert calls[0]["function"]["name"] == "kubectl"
    assert json.loads(calls[0]["function"]["arguments"])["command"] == "get ns"
    assert stack._parse_tool_calls("plain text") is None
    # dict-valued arguments are normalized to a JSON string
    calls = stack._parse_tool_calls(
        '{"tool_calls": [{"function": {"name": "f", "arguments": {"a": 1}}}]}'
    )
    assert json.loads(calls[0]["function"]["arguments"]) == {"a": 1}


def test_http_completions_and_stream(stack):
    app = build_engine_app(stack)

    async def scenario():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.get("/v1/models")
            assert (await r.json())["data"][0]["id"] == "tiny-test"

            r = await client.get("/healthz")
            health = await r.json()
            assert health["status"] == "ok"
            assert health["prefix_evictions"] >= 0  # counter exposed

            r = await client.post(
                "/v1/chat/completions",
                json={
                    "messages": [{"role": "user", "content": "hi"}],
                    "max_tokens": 3,
                },
            )
            assert r.status == 200
            data = await r.json()
            assert data["choices"][0]["message"]["role"] == "assistant"

            r = await client.post(
                "/v1/chat/completions",
                json={
                    "messages": [{"role": "user", "content": "hi"}],
                    "max_tokens": 3,
                    "stream": True,
                },
            )
            assert r.status == 200
            body = await r.text()
            lines = [ln for ln in body.splitlines() if ln.startswith("data: ")]
            assert lines[-1] == "data: [DONE]"
            first = json.loads(lines[0][len("data: ") :])
            assert first["object"] == "chat.completion.chunk"
            finals = json.loads(lines[-2][len("data: ") :])
            assert finals["choices"][0]["finish_reason"] in ("stop", "length")

            r = await client.post("/v1/chat/completions", json={})
            assert r.status == 400
        finally:
            await client.close()

    asyncio.get_event_loop_policy().new_event_loop().run_until_complete(scenario())


def test_agent_over_tpu_provider_end_to_end(fake_tools):
    """The reference's whole raison d'être, in-tree: the ReAct agent loop
    running against the TPU engine through the tpu:// scheme — zero external
    API calls. The loop requests schema-constrained decoding, so even random
    tiny weights emit parseable ToolPrompt JSON: every assistant turn in the
    transcript must parse, proving agent -> provider -> engine -> FSM-masked
    sampler -> detokenize end to end."""
    import json as _json

    from opsagent_tpu.agent.react import assistant_with_config
    from opsagent_tpu.serving.api import ServingStack, install_stack, _stacks
    from opsagent_tpu.serving.engine import Engine, EngineConfig

    cfg = EngineConfig(
        model="tiny-test", dtype=jnp.float32, tp=1, page_size=8,
        num_pages=256, max_pages_per_seq=128, max_batch_size=2,
        prefill_buckets=(256, 512, 1024), max_new_tokens_default=48,
    )
    s = ServingStack(Engine(cfg))
    install_stack("tiny-agent", s)
    try:
        fake_tools({})
        messages = [
            {"role": "system", "content": "you are a test agent"},
            {"role": "user", "content": "count namespaces"},
        ]
        out, history = assistant_with_config(
            "tpu://tiny-agent", messages, max_tokens=48, max_iterations=2
        )
        assert isinstance(out, str)
        assert history[-1]["role"] == "assistant"
        # Constrained decoding guarantees every emitted byte stays inside
        # the ToolPrompt schema's language: a completed reply parses, a
        # length-capped one is still a valid prefix (live DFA state).
        from opsagent_tpu.serving.constrained import (
            TOOLPROMPT_SCHEMA, compile_regex, schema_to_regex,
        )

        from opsagent_tpu.agent.prompts import SUMMARIZE_PROMPT

        dfa = compile_regex(schema_to_regex(TOOLPROMPT_SCHEMA))
        checked = 0
        for i, msg in enumerate(history):
            if msg["role"] != "assistant":
                continue
            # The summarization turn (triggered when a length-capped reply
            # does not parse as a ToolPrompt) is INTENTIONALLY free-form —
            # no response_format — so whether it appears depends on where
            # the 48-token budget cut the constrained replies
            # (weight-dependent). Only constrained turns carry the
            # stays-in-language guarantee.
            if (
                i > 0 and history[i - 1]["role"] == "user"
                and history[i - 1]["content"] == SUMMARIZE_PROMPT
            ):
                continue
            checked += 1
            state = dfa.run(dfa.start, msg["content"].encode())
            assert state >= 0, f"escaped the schema: {msg['content']!r}"
            try:
                parsed = _json.loads(msg["content"])
                assert set(parsed) <= {
                    "question", "thought", "action", "observation",
                    "final_answer",
                }
            except _json.JSONDecodeError:
                assert not dfa.accept[state]  # truncated, not malformed
        assert checked >= 1  # the constrained path actually ran
    finally:
        s.close()
        _stacks.pop("tiny-agent", None)


def test_prompt_too_long_fails_fast(stack):
    """A prompt that can never fit must be rejected immediately with a clear
    error, not spin in the admission queue until timeout."""
    import time

    huge = [257] + [65] * 100  # > largest bucket (64) of the test engine
    t0 = time.perf_counter()
    with pytest.raises(RuntimeError, match="exceeds|pages"):
        stack.scheduler.complete(huge, SamplingParams(max_tokens=2), timeout_s=30)
    assert time.perf_counter() - t0 < 5.0


def test_stop_strings(stack):
    text, finish = stack._finalize_text(
        [72, 101, 108, 108, 111, 33], stop=("llo",)
    )
    assert text == "He"
    assert finish == "stop"


def test_prompt_too_long_http_status_400(stack):
    """PromptTooLong is a permanent client error: the HTTP frontend must
    return 400 (derived from the typed RequestError, not string matching)."""
    app = build_engine_app(stack)

    async def scenario():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.post(
                "/v1/chat/completions",
                json={
                    "messages": [{"role": "user", "content": "x" * 500}],
                    "max_tokens": 2,
                },
            )
            assert r.status == 400, await r.text()
        finally:
            await client.close()

    asyncio.get_event_loop_policy().new_event_loop().run_until_complete(scenario())


def test_failed_admission_does_not_leak_pages(stack):
    """A request whose admission blows up mid-prefill (raising mask_fn fires
    during first-token sampling) must free its pages."""
    free_before = stack.engine.alloc.free_pages

    def bad_mask(_tokens):
        raise RuntimeError("boom")

    with pytest.raises(RuntimeError, match="admission failed|boom"):
        stack.scheduler.complete(
            [257, 1, 2, 3], SamplingParams(max_tokens=2),
            mask_fn=bad_mask, timeout_s=30,
        )
    assert stack.engine.alloc.free_pages == free_before
    assert not stack.engine.sequences


class _ScriptedScheduler:
    """Feeds a scripted token list through on_token, then completes."""

    def __init__(self, tokens):
        self.tokens = tokens

    def submit(self, req):
        for t in self.tokens:
            if req.on_token:
                req.on_token(t)
        req.finish_reason = "length"
        req.done.set()
        return req


class _FakeEngine:
    def __init__(self):
        from opsagent_tpu.serving.tokenizer import ByteTokenizer

        self.tokenizer = ByteTokenizer()
        self.cfg = EngineConfig(model="tiny-test")
        self.model_cfg = type("M", (), {"name": "tiny-test"})()


def _scripted_stack(tokens):
    s = ServingStack.__new__(ServingStack)
    s.engine = _FakeEngine()
    s.scheduler = _ScriptedScheduler(tokens)
    s.model_name = "tiny-test"
    return s


def test_stream_stop_string_straddles_chunks():
    """Stop-string holdback: 'END' arriving one byte per token must still be
    caught, and nothing after (or of) the stop string is emitted."""
    text = "Hello END tail"
    s = _scripted_stack(list(text.encode()))
    chunks = list(
        s.chat_completion_stream(
            {"messages": [{"role": "user", "content": "q"}], "stop": ["END"]}
        )
    )
    content = "".join(
        c["choices"][0]["delta"].get("content", "")
        for c in chunks
        if "choices" in c
    )
    assert content == "Hello "
    assert chunks[-1]["choices"][0]["finish_reason"] == "stop"


def test_stream_multibyte_char_split_across_tokens():
    """A UTF-8 char whose bytes span tokens must be withheld until complete,
    then emitted exactly once."""
    text = "日本語 ok"
    s = _scripted_stack(list(text.encode("utf-8")))
    chunks = list(
        s.chat_completion_stream({"messages": [{"role": "user", "content": "q"}]})
    )
    content = "".join(
        c["choices"][0]["delta"].get("content", "")
        for c in chunks
        if "choices" in c
    )
    assert content == text
    assert "�" not in content


def test_tpu_scheme_lazy_registration_fresh_process():
    """In a fresh process that never imports the serving stack, the agent's
    ChatClient must still resolve --model tpu://<name> (the provider module
    is imported lazily on first use)."""
    import subprocess
    import sys

    code = (
        "import os\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        # Env alone is not enough: a TPU-plugin sitecustomize may have
        # frozen the platform at interpreter boot (same dance as conftest),
        # and with an unreachable TPU backend init would hang, not fail.
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from opsagent_tpu.llm.client import ChatClient\n"
        "import sys\n"
        "assert not any('serving' in m for m in sys.modules), 'not lazy'\n"
        "r = ChatClient().chat_completion(\n"
        "    'tpu://tiny-test', [{'role': 'user', 'content': 'hi'}], max_tokens=2)\n"
        "assert r['choices'][0]['message'] is not None\n"
        "print('LAZY_OK')\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=300, cwd="/root/repo",
    )
    assert "LAZY_OK" in out.stdout, out.stderr[-2000:]


def test_stream_bad_sampling_param_returns_json_error(stack):
    """A translation error on a stream=true request must return a JSON error
    status, not a dead SSE connection."""
    app = build_engine_app(stack)

    async def scenario():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.post(
                "/v1/chat/completions",
                json={
                    "messages": [{"role": "user", "content": "hi"}],
                    "max_tokens": "many",
                    "stream": True,
                },
            )
            assert r.status == 400
            assert "error" in await r.json()
        finally:
            await client.close()

    asyncio.get_event_loop_policy().new_event_loop().run_until_complete(scenario())


def test_stream_prompt_too_long_http_status_400(stack):
    """An ADMISSION error (prompt exceeds the largest prefill bucket) on a
    stream=true request must surface as HTTP 400, not a 200 SSE stream with
    an in-stream error event."""
    app = build_engine_app(stack)

    async def scenario():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.post(
                "/v1/chat/completions",
                json={
                    "messages": [{"role": "user", "content": "x" * 500}],
                    "max_tokens": 2,
                    "stream": True,
                },
            )
            assert r.status == 400, await r.text()
            assert "error" in await r.json()
        finally:
            await client.close()

    asyncio.get_event_loop_policy().new_event_loop().run_until_complete(scenario())


def test_raising_stream_callback_does_not_leak_pages(stack):
    """A stream/on_token callback that raises on the FIRST token (delivered
    during admission) must not leak pages or a zombie Sequence."""
    free_before = stack.engine.alloc.free_pages

    def bad_stream(_tok):
        raise RuntimeError("stream boom")

    with pytest.raises(RuntimeError, match="admission failed|stream boom"):
        stack.scheduler.complete(
            [257, 1, 2, 3], SamplingParams(max_tokens=2),
            on_token=bad_stream, timeout_s=30,
        )
    assert stack.engine.alloc.free_pages == free_before
    assert not stack.engine.sequences


def test_multibyte_stop_string_halts_engine_side(stack):
    """A CJK stop string (3 UTF-8 byte-tokens per char) must stop generation
    engine-side well before max_tokens (token window sized in bytes)."""
    from opsagent_tpu.serving.engine import Sequence

    seq = Sequence(seq_id=0, prompt_len=1, params=SamplingParams(stop=("終了" * 5,)))
    seq.tokens = list(("x" + "終了" * 5).encode("utf-8"))
    assert stack.engine._hit_stop_string(seq)


def test_profile_endpoints(stack, tmp_path, monkeypatch):
    """/v1/profile/{start,stop}: operator-gated jax.profiler capture around
    live traffic. Without --profile-dir the start endpoint refuses (403) —
    a network client must not get a filesystem-write primitive; with it, a
    start/traffic/stop cycle writes a capture and double-stop is a 409."""
    app = build_engine_app(stack)

    async def scenario():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            monkeypatch.delenv("OPSAGENT_PROFILE_DIR", raising=False)
            r = await client.post("/v1/profile/start", json={"logdir": "/etc"})
            assert r.status == 403  # client-supplied logdir is never honored

            logdir = str(tmp_path / "trace")
            monkeypatch.setenv("OPSAGENT_PROFILE_DIR", logdir)
            r = await client.post("/v1/profile/start")
            assert r.status == 200
            assert (await r.json())["logdir"] == logdir

            r = await client.post(
                "/v1/chat/completions",
                json={"messages": [{"role": "user", "content": "hi"}],
                      "max_tokens": 2},
            )
            assert r.status == 200

            r = await client.post("/v1/profile/stop")
            assert r.status == 200
            files = [
                os.path.join(root, f)
                for root, _, fs in os.walk(logdir) for f in fs
            ]
            assert files, "trace capture wrote no files"

            r = await client.post("/v1/profile/stop")
            assert r.status == 409  # not tracing
        finally:
            await client.close()

    asyncio.get_event_loop_policy().new_event_loop().run_until_complete(scenario())
