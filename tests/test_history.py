"""Telemetry time machine (ISSUE 18): TelemetryHistory's tiered rings
(counter deltas, rollup conservation, byte bound, query/rebucket, rate),
the /api/metrics/history endpoints (replica handler + fleet-aggregated
router view with {replica_id}: prefixes and skew-corrected timestamps),
the SLO watchdog's history-backed decode rate + per-class report, the
``slo-check --class`` gate, tail-based trace retention (the p=0.01
acceptance criterion: every breached/errored/failed-over request still
answers /api/timeline/{id}), the anomaly dump's appended history block,
and the ``opsagent top`` cockpit rendering >=3 frames against a live
2-replica fleet."""

import asyncio
import io
import json
import threading
import urllib.request

import jax.numpy as jnp
import pytest
from aiohttp.test_utils import TestClient, TestServer

from opsagent_tpu import obs
from opsagent_tpu.cli.slocheck import _check_class
from opsagent_tpu.cli.top import run_top, sparkline
from opsagent_tpu.obs.history import (
    POINT_BYTES,
    TIER_SPECS,
    TelemetryHistory,
    parse_query,
)
from opsagent_tpu.serving import faults
from opsagent_tpu.serving.api import ServingStack
from opsagent_tpu.serving.engine import Engine, EngineConfig
from opsagent_tpu.serving.fleet.registry import ReplicaInfo
from opsagent_tpu.serving.fleet.router import FleetRouter, build_router_app

BASE = dict(
    model="tiny-test", dtype=jnp.float32, tp=1, page_size=4,
    num_pages=256, max_pages_per_seq=64, max_batch_size=4,
    prefill_buckets=(16, 32, 64), decode_block=4, seed=0,
)

CHAT = {
    "messages": [{"role": "user", "content": "hello"}],
    "max_tokens": 4, "temperature": 0,
}

T0 = 1_700_000_000.0


def run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def _fleet(n=2):
    router = FleetRouter()
    stacks = []
    for i in range(n):
        stack = ServingStack(Engine(EngineConfig(**BASE)))
        stacks.append(stack)
        router.add_local(stack, f"r{i}")
    return router, stacks


def _close(stacks):
    for s in stacks:
        s.close()


def _serve_router_on_port(router):
    """Run the router app on a real localhost port (urllib cannot talk
    to aiohttp's TestClient transport). Returns (base_url, stop_fn)."""
    app = build_router_app(router)
    loop = asyncio.new_event_loop()
    runner_box = {}

    async def _start():
        from aiohttp import web

        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        runner_box["runner"] = runner
        runner_box["port"] = runner.addresses[0][1]

    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()
    asyncio.run_coroutine_threadsafe(_start(), loop).result(timeout=30)

    def stop():
        async def _stop():
            await runner_box["runner"].cleanup()

        asyncio.run_coroutine_threadsafe(_stop(), loop).result(timeout=30)
        loop.call_soon_threadsafe(loop.stop)
        t.join(timeout=10)

    return f"http://127.0.0.1:{runner_box['port']}", stop


def _counter(total_box):
    """A counter reader driven by mutating total_box["v"]."""
    return lambda: total_box["v"]


# -- the store itself (synthetic clock, no engines) ---------------------------
class TestTelemetryHistory:
    def test_counter_records_deltas_not_totals(self):
        h = TelemetryHistory(max_bytes=1 << 20, interval_s=1.0)
        box = {"v": 100.0}
        h.register("tokens", "counter", _counter(box))
        h.sample(now=T0)          # first sweep: baseline only, no point
        box["v"] = 105.0
        h.sample(now=T0 + 1)
        box["v"] = 112.0
        h.sample(now=T0 + 2)
        pts = h.query(series=["tokens"], since=60.0, now=T0 + 2)[
            "series"]["tokens"]["points"]
        assert [p[1] for p in pts] == [5.0, 7.0]
        assert [p[0] for p in pts] == [T0 + 1, T0 + 2]

    def test_counter_reset_clamps_to_zero_delta(self):
        h = TelemetryHistory(max_bytes=1 << 20)
        box = {"v": 50.0}
        h.register("tokens", "counter", _counter(box))
        h.sample(now=T0)
        box["v"] = 3.0            # process restart: total went backwards
        h.sample(now=T0 + 1)
        pts = h.query(series=["tokens"], since=60.0, now=T0 + 1)[
            "series"]["tokens"]["points"]
        assert [p[1] for p in pts] == [0.0]

    def test_rollup_conserves_counter_sum_across_all_tiers(self):
        """70 min of 1 Hz sweeps at +7 tokens each populates all three
        tiers; summing every surviving delta still equals exactly what
        the counter advanced by — rollup aggregates, never loses."""
        h = TelemetryHistory(max_bytes=8 << 20, interval_s=1.0)
        box = {"v": 0.0}
        h.register("tokens", "counter", _counter(box))
        n = 70 * 60
        for i in range(n):
            box["v"] += 7.0
            h.sample(now=T0 + i)
        per_tier = h.stats()["points_per_tier"]
        assert per_tier[1] > 0 and per_tier[2] > 0, per_tier
        # Tier 0 holds only its 300 s horizon (plus rollup slack).
        assert per_tier[0] <= 2 * (TIER_SPECS[0][1] + TIER_SPECS[1][0])
        pts = h.query(series=["tokens"], since=n + 10, now=T0 + n - 1)[
            "series"]["tokens"]["points"]
        total = sum(p[1] for p in pts)
        assert abs(total - 7.0 * (n - 1)) < 1e-6  # first sweep = baseline

    def test_step_rebucket_is_exact_for_counters(self):
        h = TelemetryHistory(max_bytes=8 << 20)
        box = {"v": 0.0}
        h.register("tokens", "counter", _counter(box))
        n = 600
        for i in range(n):
            box["v"] += 7.0
            h.sample(now=T0 + i)
        pts = h.query(
            series=["tokens"], since=n + 10, step=60.0, now=T0 + n - 1,
        )["series"]["tokens"]["points"]
        # Interior buckets each cover 60 full sweeps of +7.
        assert pts[2:-2]
        assert all(p[1] == 60 * 7.0 for p in pts[2:-2]), pts

    def test_gauge_rebucket_averages(self):
        h = TelemetryHistory(max_bytes=1 << 20)
        vals = iter([2.0, 4.0, 6.0, 8.0])
        h.register("occ", "gauge", lambda: next(vals))
        for i in range(4):
            h.sample(now=T0 + i)
        pts = h.query(
            series=["occ"], since=60.0, step=10.0, now=T0 + 3,
        )["series"]["occ"]["points"]
        assert len(pts) == 1 and pts[0][1] == pytest.approx(5.0)

    def test_byte_budget_evicts_oldest_but_never_overruns(self):
        h = TelemetryHistory(max_bytes=4096)
        box = {"v": 0.0}
        h.register("tokens", "counter", _counter(box))
        h.register("occ", "gauge", lambda: 1.0)
        for i in range(2000):
            box["v"] += 1.0
            h.sample(now=T0 + i)
        st = h.stats()
        assert st["evicted"] > 0
        assert st["bytes"] <= st["max_bytes"] == 4096
        assert st["bytes"] == sum(st["points_per_tier"]) * POINT_BYTES
        # The NEWEST points survive eviction.
        pts = h.query(series=["tokens"], since=10.0, now=T0 + 1999)[
            "series"]["tokens"]["points"]
        assert pts and pts[-1][0] == T0 + 1999

    def test_rate_and_window_sum(self):
        h = TelemetryHistory(max_bytes=1 << 20)
        box = {"v": 0.0}
        h.register("tokens", "counter", _counter(box))
        h.sample(now=T0)
        assert h.rate("tokens", 60.0, now=T0) is None  # no points yet
        for i in range(1, 11):
            box["v"] += 5.0
            h.sample(now=T0 + i)
        assert h.rate("tokens", 60.0, now=T0 + 10) == pytest.approx(5.0)
        assert h.window_sum("tokens", 60.0, now=T0 + 10) == 50.0
        assert h.window_sum("tokens", 3.5, now=T0 + 10) == 20.0
        assert h.rate("ghost", 60.0, now=T0 + 10) is None
        assert h.window_sum("ghost", 60.0, now=T0 + 10) == 0.0

    def test_query_since_filters_and_register_is_idempotent(self):
        h = TelemetryHistory(max_bytes=1 << 20)
        box = {"v": 0.0}
        h.register("tokens", "counter", _counter(box))
        for i in range(20):
            box["v"] += 1.0
            h.sample(now=T0 + i)
        # Re-registering keeps the ring (modules reload across tests).
        h.register("tokens", "counter", _counter(box))
        recent = h.query(series=["tokens"], since=5.0, now=T0 + 19)[
            "series"]["tokens"]["points"]
        assert len(recent) == 6  # t in [14 .. 19]
        out = h.query(series=["tokens", "ghost"], since=60.0, now=T0 + 19)
        assert list(out["series"]) == ["tokens"]
        assert out["tiers"][0] == {"step_s": 1.0, "horizon_s": 300.0}

    def test_parse_query_grammar(self):
        kw = parse_query({"series": "a, b,", "since": "60", "step": "10"})
        assert kw == {"series": ["a", "b"], "since": 60.0, "step": 10.0}
        assert parse_query({}) == {}
        with pytest.raises(ValueError):
            parse_query({"since": "banana"})
        with pytest.raises(ValueError):
            parse_query({"step": "x"})

    def test_reader_failure_skips_series_not_the_sweep(self):
        h = TelemetryHistory(max_bytes=1 << 20)

        def boom():
            raise RuntimeError("reader died")

        h.register("bad", "gauge", boom)
        h.register("good", "gauge", lambda: 1.0)
        h.sample(now=T0)
        out = h.query(since=60.0, now=T0)["series"]
        assert out["good"]["points"] and not out["bad"]["points"]


# -- watchdog decode rate + per-class report (satellite 1) --------------------
class TestWatchdogHistoryIntegration:
    def test_decode_rate_rides_the_history_sampler(self):
        import time as _time

        h = obs.history.get_history()
        now = _time.time()
        h.sample(now=now - 2)            # baseline sweep
        obs.DECODE_TOKENS.inc(50)
        h.sample(now=now - 1)
        obs.DECODE_TOKENS.inc(70)
        h.sample(now=now)
        rate = obs.slo.get_watchdog()._decode_rate()
        assert rate == pytest.approx(70.0, rel=0.05)

    def test_class_report_windows_attainment_and_burn(self):
        import time as _time

        h = obs.history.get_history()
        now = _time.time()
        h.sample(now=now - 2)
        for _ in range(9):
            obs.CLASS_REQUESTS.inc(
                **{"class": "interactive", "outcome": "completed"}
            )
        obs.CLASS_REQUESTS.inc(
            **{"class": "interactive", "outcome": "error"}
        )
        obs.CLASS_TTFT_SECONDS.observe(0.05, **{"class": "interactive"})
        h.sample(now=now - 1)
        h.sample(now=now)
        rows = obs.slo.get_watchdog().class_report()
        assert [r["class"] for r in rows] == ["interactive"]
        r = rows[0]
        assert r["requests"] == 10 and r["bad"] == 1
        assert r["attainment"] == pytest.approx(0.9)
        assert r["ttft_p95_ms"] is not None
        w5 = r["windows"]["5m"]
        assert w5["requests"] == 10
        # (1 - 0.9) / 0.01 budget = 10x burn.
        assert w5["burn_rate"] == pytest.approx(10.0)
        full = obs.slo.evaluate()
        assert full["classes"] == rows or full["classes"]
        assert full["error_budget"] == pytest.approx(0.01)

    def test_slo_check_class_gate_exit_codes(self, capsys):
        healthy = {
            "error_budget": 0.01,
            "classes": [{
                "class": "interactive", "requests": 100,
                "attainment": 0.995,
                "windows": {"5m": {
                    "requests": 100, "attainment": 0.995, "burn_rate": 0.5,
                }},
            }],
        }
        assert _check_class(healthy, "interactive") == 0
        burning = {
            "error_budget": 0.01,
            "classes": [{
                "class": "batch", "requests": 40, "attainment": 0.999,
                "windows": {"5m": {
                    "requests": 40, "attainment": 0.9, "burn_rate": 10.0,
                }},
            }],
        }
        assert _check_class(burning, "batch") == 1
        low_attainment = {
            "error_budget": 0.01,
            "classes": [{
                "class": "batch", "requests": 40, "attainment": 0.5,
                "windows": {},
            }],
        }
        assert _check_class(low_attainment, "batch") == 1
        assert _check_class({"classes": []}, "background") == 2
        capsys.readouterr()


# -- endpoints: replica handler, router passthrough, fleet aggregation --------
class TestHistoryEndpoints:
    def test_router_endpoint_serves_history_and_rejects_bad_query(self):
        router, stacks = _fleet(1)
        app = build_router_app(router)

        async def scenario():
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                obs.history.get_history().sample()
                r = await client.get(
                    "/api/metrics/history?since=60&step=10"
                )
                assert r.status == 200
                body = await r.json()
                assert "decode_tokens" in body["series"]
                assert body["tiers"][0]["step_s"] == 1.0
                assert body["replicas"] == ["r0"]
                r = await client.get("/api/metrics/history?since=banana")
                assert r.status == 400
                assert "error" in await r.json()
            finally:
                await client.close()

        try:
            run(scenario())
        finally:
            _close(stacks)

    def test_server_handler_parses_the_same_grammar(self):
        """The per-replica server handler shares parse_query with the
        router — same 400 on the same malformed input."""
        from opsagent_tpu.server import handlers

        class _Req:
            def __init__(self, q):
                self.query = q

        async def scenario():
            obs.history.get_history().sample()
            ok = await handlers.history_get(_Req({"since": "60"}))
            assert ok.status == 200
            assert "series" in json.loads(ok.text)
            bad = await handlers.history_get(_Req({"step": "banana"}))
            assert bad.status == 400

        run(scenario())

    def test_fleet_aggregation_prefixes_and_skew_corrects_remote_series(
        self, monkeypatch
    ):
        """Remote replica series come back {replica_id}:{name} with
        timestamps shifted by -offset into the router's clock; local
        series stay unprefixed (in-process replicas share the router's
        store)."""

        class StubRemote:
            def history(self, series=None, since=300.0, step=None):
                return {"series": {
                    "decode_tokens": {
                        "kind": "counter",
                        "points": [[T0 + 5.0, 7.0], [T0 + 6.0, 7.0]],
                    },
                }}

        router, stacks = _fleet(1)
        try:
            info = ReplicaInfo(replica_id="rr", url="http://fake")
            info.handle = StubRemote()
            router.registry.register(info)
            monkeypatch.setattr(
                router.registry, "clock_offsets",
                lambda: {"rr": 2.0, "r0": 0.0},
            )
            obs.history.get_history().sample()
            out = router.metrics_history(since=600.0)
            assert set(out["replicas"]) == {"r0", "rr"}
            assert "decode_tokens" in out["series"]          # local, bare
            remote = out["series"]["rr:decode_tokens"]
            assert remote["kind"] == "counter"
            # replica wall 2 s ahead -> shifted back into router time.
            assert [p[0] for p in remote["points"]] == [T0 + 3.0, T0 + 4.0]
            assert out["clock_offset_s"]["rr"] == 2.0
        finally:
            _close(stacks)

    def test_slo_aggregate_merges_remote_class_reports(self):
        """A real HTTP fleet classifies completions in the replica
        processes: the router's /api/slo folds those per-replica class
        reports into one fleet view (sums, recomputed attainment,
        worst-replica p95, request-weighted windows)."""
        from opsagent_tpu.serving.fleet.router import _merge_class_reports

        local = [{
            "class": "interactive", "requests": 10, "bad": 1,
            "attainment": 0.9, "ttft_p95_ms": 100.0, "itl_p95_ms": None,
            "outcomes": {"completed": 9, "error": 1},
            "windows": {"5m": {
                "requests": 10, "attainment": 0.9, "burn_rate": 10.0,
            }},
        }]
        remote = [{
            "class": "interactive", "requests": 30, "bad": 0,
            "attainment": 1.0, "ttft_p95_ms": 250.0, "itl_p95_ms": 40.0,
            "outcomes": {"completed": 30},
            "windows": {"5m": {
                "requests": 30, "attainment": 1.0, "burn_rate": 0.0,
            }},
        }, {
            "class": "batch", "requests": 5, "bad": 0,
            "attainment": 1.0, "ttft_p95_ms": None, "itl_p95_ms": None,
            "outcomes": {"completed": 5}, "windows": {},
        }]
        rows = _merge_class_reports([local, remote], budget=0.01)
        assert [r["class"] for r in rows] == ["interactive", "batch"]
        inter = rows[0]
        assert inter["requests"] == 40 and inter["bad"] == 1
        assert inter["attainment"] == pytest.approx(39 / 40)
        assert inter["ttft_p95_ms"] == 250.0   # worst replica
        assert inter["itl_p95_ms"] == 40.0
        assert inter["outcomes"] == {"completed": 39, "error": 1}
        w5 = inter["windows"]["5m"]
        assert w5["requests"] == 40
        assert w5["attainment"] == pytest.approx(0.975)
        assert w5["burn_rate"] == pytest.approx(2.5)
        assert _merge_class_reports([[], []], 0.01) == []

    def test_aggregation_degrades_when_a_remote_fails(self, monkeypatch):
        class DeadRemote:
            def history(self, **kw):
                raise OSError("connection refused")

        router, stacks = _fleet(1)
        try:
            info = ReplicaInfo(replica_id="dead", url="http://fake")
            info.handle = DeadRemote()
            router.registry.register(info)
            out = router.metrics_history(since=60.0)
            assert "dead" in out["replicas"]
            assert not any(k.startswith("dead:") for k in out["series"])
        finally:
            _close(stacks)


# -- tail-based retention: the p=0.01 acceptance criterion --------------------
class TestTailRetention:
    def test_anomalous_requests_always_answer_timeline_at_p001(
        self, tmp_path, monkeypatch
    ):
        """Forced load at trace-sample p=0.01: healthy requests are
        (mostly) dropped, yet 100% of breached / errored / failed-over
        requests still return a full /api/timeline/{id} over HTTP."""
        monkeypatch.setenv("OPSAGENT_FLIGHT_DIR", str(tmp_path))
        monkeypatch.setenv("OPSAGENT_SLO_TTFT_MS", "60000")
        obs.trace.set_sample_probability(0.01)
        router, stacks = _fleet(2)
        url, stop = _serve_router_on_port(router)
        anomalous_ids = []
        try:
            # Phase 1 — healthy traffic: nothing breaches, so retention
            # is a pure p=0.01 draw and almost everything is dropped.
            for _ in range(25):
                resp = router.complete(dict(CHAT))
                assert resp["choices"][0]["message"]["content"]
            dropped = obs.TRACE_RETENTION.value(decision="dropped")
            assert dropped > 0, "p=0.01 must shed healthy traces"

            # Phase 2a — TTFT breach: every request now blows the SLO
            # and its anomaly event pins the trace.
            monkeypatch.setenv("OPSAGENT_SLO_TTFT_MS", "0.0001")
            for _ in range(3):
                resp = router.complete(dict(CHAT))
                anomalous_ids.append(resp["id"])
            monkeypatch.setenv("OPSAGENT_SLO_TTFT_MS", "60000")

            # Phase 2b — mid-stream failover: the journey is marked
            # anomalous on the resume path.
            faults.configure("fleet.stream_disconnect@5")
            try:
                chunks = list(router.complete_stream({
                    "messages": [
                        {"role": "user", "content": "failover me"}
                    ],
                    "max_tokens": 12, "temperature": 0, "stream": True,
                }))
            finally:
                faults.reset()
            assert all("error" not in c for c in chunks)
            anomalous_ids.append(chunks[0]["id"])
            assert obs.FLEET_FAILOVERS.value() >= 1

            kept = obs.TRACE_RETENTION.value(decision="kept_anomalous")
            assert kept >= len(anomalous_ids)

            # The criterion: every anomalous id answers over HTTP.
            for rid in anomalous_ids:
                with urllib.request.urlopen(
                    f"{url}/api/timeline/{rid}", timeout=10
                ) as r:
                    assert r.status == 200
                    tl = json.loads(r.read().decode())
                assert tl.get("request_id") == rid or tl.get("trace")
        finally:
            stop()
            _close(stacks)
            obs.trace.set_sample_probability(None)

    def test_anomaly_dump_carries_the_history_leadup(
        self, tmp_path, monkeypatch
    ):
        """Satellite 2: the flight dump written on a breach appends a
        {"kind": "history"} block — the last 60 s of every series —
        so postmortems need no live scrape."""
        import time as _time

        monkeypatch.setenv("OPSAGENT_FLIGHT_DIR", str(tmp_path))
        monkeypatch.setenv("OPSAGENT_SLO_TTFT_MS", "0.0001")
        h = obs.history.get_history()
        now = _time.time()
        h.sample(now=now - 2)
        obs.DECODE_TOKENS.inc(11)
        h.sample(now=now - 1)
        obs.DECODE_TOKENS.inc(13)
        h.sample(now=now)
        router, stacks = _fleet(1)
        try:
            router.complete(dict(CHAT))  # breaches -> anomaly -> dump
        finally:
            _close(stacks)
        dumps = sorted(tmp_path.glob("flight-*.jsonl"))
        assert dumps, "breach must dump the flight ring"
        blocks = []
        for p in dumps:
            for line in p.read_text().splitlines():
                d = json.loads(line)
                if d.get("kind") == "history":
                    blocks.append(d)
        assert blocks, "anomaly dump must append the history block"
        pts = blocks[-1]["series"]["decode_tokens"]["points"]
        assert sum(p[1] for p in pts) == pytest.approx(24.0)


# -- the cockpit: opsagent top against a live fleet ---------------------------
class TestTopCockpit:
    def test_sparkline_shapes(self):
        assert sparkline([], width=8) == "·" * 8
        line = sparkline([[float(i), float(i)] for i in range(24)], width=8)
        assert len(line) == 8
        assert line[0] <= line[-1]  # ramp renders as a ramp

    def test_top_renders_three_frames_against_a_live_fleet(
        self, tmp_path, monkeypatch
    ):
        """The acceptance gate: >=3 consecutive frames from a live
        in-process 2-replica fleet over real HTTP (no TTY), showing
        per-replica health and per-class SLO rows."""
        monkeypatch.setenv("OPSAGENT_FLIGHT_DIR", str(tmp_path))
        monkeypatch.setenv("OPSAGENT_SLO_TTFT_MS", "60000")
        router, stacks = _fleet(2)
        url, stop = _serve_router_on_port(router)
        try:
            for _ in range(2):
                resp = router.complete(dict(CHAT))
                assert resp["choices"][0]["message"]["content"]
            obs.history.get_history().sample()
            buf = io.StringIO()
            rc = run_top(
                url, interval_s=0.05, frames=3, out=buf, color=False,
            )
            out = buf.getvalue()
            assert rc == 0
            assert out.count("opsagent top") == 3
            assert out.count("-" * 72) == 2  # non-TTY frame separator
            assert "\x1b[" not in out        # color=False: no ANSI
            assert "r0" in out and "r1" in out
            assert "healthy" in out
            assert "interactive" in out      # per-class SLO row
            assert "slo classes" in out and "anomaly tail" in out
        finally:
            stop()
            _close(stacks)

    def test_top_returns_one_when_nothing_answers(self):
        buf = io.StringIO()
        rc = run_top(
            "http://127.0.0.1:9",  # discard port: nothing listens
            interval_s=0.01, frames=2, out=buf, color=False,
        )
        assert rc == 1
        assert "opsagent top" in buf.getvalue()  # frames still render
