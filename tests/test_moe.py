"""MoE layer stack (DeepSeek-style): routing math, oracle equivalence of the
serving paths, engine generation, and sharded execution on the 8-device mesh.
Capability target: BASELINE.json config 3 (DeepSeek function calling)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from opsagent_tpu.models import llama
from opsagent_tpu.models.config import get_config_preset


CFG = get_config_preset("tiny-moe")


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)


def test_param_tree_shapes(params):
    m = CFG.moe
    Lm = CFG.num_layers - CFG.moe_layer_start
    fe = m.expert_intermediate_size
    assert params["layers"]["wg"].shape[0] == CFG.moe_layer_start
    assert params["moe_layers"]["eg"].shape == (
        Lm, m.num_experts, CFG.hidden_size, fe
    )
    assert params["moe_layers"]["router"].shape == (
        Lm, CFG.hidden_size, m.num_experts
    )
    assert params["moe_layers"]["sg"].shape == (
        Lm, CFG.hidden_size, fe * m.num_shared_experts
    )
    # Specs tree must mirror the params tree exactly.
    jax.tree.map(lambda a, b: None, params, llama.param_specs(CFG))


def test_router_topk_normalized(params):
    """Top-k combine weights are nonnegative, sum to 1, with exactly k live."""
    lp = jax.tree.map(lambda a: a[0], params["moe_layers"])
    h = jax.random.normal(jax.random.PRNGKey(1), (2, 5, CFG.hidden_size))
    m = CFG.moe
    logits = h.astype(jnp.float32) @ lp["router"]
    probs = jax.nn.softmax(logits, axis=-1)
    vals, idx = jax.lax.top_k(probs, m.num_experts_per_token)
    w = vals / vals.sum(-1, keepdims=True)
    np.testing.assert_allclose(np.asarray(w.sum(-1)), 1.0, rtol=1e-5)
    assert (np.asarray(vals) > 0).all()


def test_prefill_decode_match_forward_full(params):
    """The serving path (prefill + N decode steps) must reproduce the
    all-positions oracle through the MoE stack."""
    rng = np.random.default_rng(0)
    n = 12
    toks = rng.integers(1, CFG.vocab_size, n).astype(np.int32)

    # Oracle: all-positions logits.
    full = llama.forward_full(
        params, CFG, jnp.asarray(toks[None, :]), dtype=jnp.float32
    )

    # Serving: prefill 8, then 4 decode steps.
    P, NP, MaxP = 4, 16, 8
    cache = llama.make_cache(CFG, NP, P, dtype=jnp.float32)
    table = np.full((1, MaxP), -1, np.int32)
    table[0, :4] = [0, 1, 2, 3]
    buck = np.zeros((1, 16), np.int32)
    buck[0, :8] = toks[:8]
    logits, cache = llama.prefill(
        params, CFG, jnp.asarray(buck), jnp.asarray([8], jnp.int32),
        cache, jnp.asarray(table), dtype=jnp.float32,
    )
    np.testing.assert_allclose(
        np.asarray(logits[0]), np.asarray(full[0, 7]), rtol=2e-4, atol=2e-4
    )
    for i in range(8, n):
        logits, cache = llama.decode_step(
            params, CFG, jnp.asarray([toks[i]], jnp.int32),
            jnp.asarray([i], jnp.int32), cache, jnp.asarray(table),
            jnp.asarray([True]), dtype=jnp.float32,
        )
        np.testing.assert_allclose(
            np.asarray(logits[0]), np.asarray(full[0, i]),
            rtol=2e-4, atol=2e-4,
        )


def test_engine_generates_with_moe():
    from opsagent_tpu.serving.engine import Engine, EngineConfig
    from opsagent_tpu.serving.sampler import SamplingParams

    eng = Engine(EngineConfig(
        model="tiny-moe", dtype=jnp.float32, page_size=8, num_pages=64,
        max_pages_per_seq=8, max_batch_size=2, prefill_buckets=(16, 32),
    ))
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, 500, 10).tolist(), rng.integers(1, 500, 20).tolist()]
    outs = eng.generate(prompts, SamplingParams(temperature=0.0, max_tokens=5))
    assert all(1 <= len(o) <= 5 for o in outs)
    # Greedy determinism through the MoE stack (fresh engine, same prompts).
    outs2 = eng.generate(prompts, SamplingParams(temperature=0.0, max_tokens=5))
    assert outs == outs2


def test_moe_checkpoint_roundtrip(tmp_path, params):
    """save_checkpoint must emit the full MoE tree (router, experts, shared)
    in DeepSeek HF naming, and load_checkpoint must rebuild it exactly."""
    from opsagent_tpu.models.loader import load_checkpoint, save_checkpoint

    path = str(tmp_path / "moe.safetensors")
    save_checkpoint(path, params)
    reloaded = load_checkpoint(path, CFG, dtype=jnp.float32)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=1e-6, atol=1e-6,
        ),
        params,
        reloaded,
    )


def test_moe_aux_loss_reported():
    from opsagent_tpu.parallel.mesh import make_mesh
    from opsagent_tpu.training import TrainConfig, init_train_state, make_train_step

    mesh = make_mesh(tp=1, dp=1, sp=1, devices=jax.devices()[:1])
    tc = TrainConfig(remat=False)
    params, opt_state = init_train_state(
        CFG, tc, mesh, jax.random.PRNGKey(0), dtype=jnp.float32
    )
    step = make_train_step(CFG, tc, mesh, dtype=jnp.float32)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(1, 500, (2, 16)), jnp.int32
    )
    _, _, metrics = step(params, opt_state, tokens, jnp.ones((2, 16)))
    aux = float(metrics["moe_aux"])
    # Switch aux is >= 1 (equality at perfectly uniform routing), summed
    # over the MoE layers.
    assert aux >= 1.0


def test_sharded_moe_training_step():
    """Full training step over tiny-moe on the virtual 8-device mesh: the
    expert TP shardings must compile and produce a finite loss."""
    from opsagent_tpu.parallel.mesh import make_mesh
    from opsagent_tpu.training import TrainConfig, init_train_state, make_train_step

    mesh = make_mesh(tp=2, dp=2, sp=2)
    tc = TrainConfig(remat=True)
    params, opt_state = init_train_state(
        CFG, tc, mesh, jax.random.PRNGKey(0), dtype=jnp.float32
    )
    step = make_train_step(CFG, tc, mesh, dtype=jnp.float32)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(1, 500, (4, 32)), jnp.int32)
    mask = jnp.ones((4, 32), jnp.float32)
    params, opt_state, metrics = step(params, opt_state, tokens, mask)
    loss = float(metrics["loss"])
    assert np.isfinite(loss)


class TestCombineWeightSemantics:
    """Combine-weight flags must match the checkpoint's HF config: DeepSeek-
    MoE-16B/V2-Lite ship norm_topk_prob=false (raw softmax probs); V3 ships
    norm_topk_prob=true with routed_scaling_factor=2.5 (advisor finding:
    unconditional renormalization corrupts DeepSeek-16B generation)."""

    def _outputs(self, flags, h, params):
        from dataclasses import replace

        cfg = replace(CFG, moe=replace(CFG.moe, **flags))
        lp = jax.tree.map(lambda a: a[0], params["moe_layers"])
        out, _ = llama._moe_mlp(h, lp, cfg)
        return np.asarray(out)

    def test_raw_vs_renormalized_differ_by_topk_mass(self, params):
        h = jax.random.normal(
            jax.random.PRNGKey(3), (2, 4, CFG.hidden_size), jnp.float32
        )
        raw = self._outputs({"norm_topk_prob": False}, h, params)
        renorm = self._outputs({"norm_topk_prob": True}, h, params)
        # Renormalization divides combine weights by sum(top-k probs) < 1,
        # so the routed contribution grows; outputs must differ.
        assert not np.allclose(raw, renorm)

    def test_routed_scaling_factor_scales_routed_path(self, params):
        h = jax.random.normal(
            jax.random.PRNGKey(4), (1, 3, CFG.hidden_size), jnp.float32
        )
        base = self._outputs({}, h, params)
        scaled = self._outputs({"routed_scaling_factor": 2.5}, h, params)
        # Shared-expert path is unscaled; isolate the routed path by diff.
        shared_only = self._outputs({"routed_scaling_factor": 0.0}, h, params)
        np.testing.assert_allclose(
            scaled - shared_only, 2.5 * (base - shared_only),
            rtol=2e-5, atol=2e-6,
        )

    def test_deepseek_16b_preset_uses_raw_probs(self):
        cfg = get_config_preset("deepseek-moe-16b")
        assert cfg.moe.norm_topk_prob is False
        assert cfg.moe.routed_scaling_factor == 1.0


class TestGroupedDispatch:
    """VERDICT item 7: expert FLOPs must scale with top-k, not E. The
    grouped capacity dispatch must reproduce the all-experts scan exactly
    when capacity covers every assignment."""

    def _cfg(self, **flags):
        from dataclasses import replace

        return replace(CFG, moe=replace(CFG.moe, **flags))

    def test_grouped_matches_scan_when_capacity_covers(self, params):
        lp = jax.tree.map(lambda a: a[0], params["moe_layers"])
        h = jax.random.normal(
            jax.random.PRNGKey(7), (4, 16, CFG.hidden_size), jnp.float32
        )
        # capacity_factor E/k => C == T: nothing can drop; outputs exact.
        scan_cfg = self._cfg(grouped_dispatch_min_tokens=0)
        grp_cfg = self._cfg(
            grouped_dispatch_min_tokens=1,
            capacity_factor=CFG.moe.num_experts / CFG.moe.num_experts_per_token,
        )
        want, aux_w = llama._moe_mlp(h, lp, scan_cfg)
        got, aux_g = llama._moe_mlp(h, lp, grp_cfg)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
        )
        np.testing.assert_allclose(float(aux_w), float(aux_g), rtol=1e-6)

    def test_grouped_flops_scale_with_capacity(self):
        """Regression guard on the actual compiled program: XLA cost
        analysis of the grouped path must report far fewer FLOPs than the
        all-experts scan on the same shapes (the VERDICT item's point —
        expert compute scales with top-k*capacity, not num_experts)."""
        # DeepSeek-shaped expert count (E >> k) — at tiny-moe's E=4, k=2
        # the dispatch bookkeeping outweighs the expert saving; the FLOPs
        # win this guards is the many-experts regime (config 3 is k=6 of
        # E=64).
        from dataclasses import replace

        from opsagent_tpu.models.config import MoEConfig

        def cfg_with(**flags):
            return replace(CFG, moe=MoEConfig(
                num_experts=16, num_experts_per_token=2,
                num_shared_experts=1, expert_intermediate_size=64, **flags,
            ))

        params = llama.init_params(
            cfg_with(), jax.random.PRNGKey(0), jnp.float32
        )
        lp = jax.tree.map(lambda a: a[0], params["moe_layers"])
        h = jax.random.normal(
            jax.random.PRNGKey(8), (8, 16, CFG.hidden_size), jnp.float32
        )

        def flops_of(cfg):
            fn = jax.jit(lambda h, lp: llama._moe_mlp(h, lp, cfg)[0])
            cost = fn.lower(h, lp).compile().cost_analysis()
            if isinstance(cost, list):  # older jax returns one per device
                cost = cost[0]
            return float(cost["flops"])

        grouped = cfg_with(
            grouped_dispatch_min_tokens=1, capacity_factor=1.25
        )
        grouped_flops = flops_of(grouped)
        # The scan path is useless as a cost baseline (XLA cost analysis
        # counts a while-loop body once, not per trip), so compare against
        # the ANALYTIC all-experts expert compute: E * T * 3 matmuls of
        # [d, fe]. Grouped runs E * C slots with C = ceil(T*k/E * cf), or
        # ~0.16x here — assert well under the dense count, which fails if
        # the path regresses to computing every expert on every token.
        m = grouped.moe
        T = 8 * 16
        dense_expert_flops = (
            m.num_experts * T * 3 * 2 * CFG.hidden_size
            * m.expert_intermediate_size
        )
        assert grouped_flops < 0.5 * dense_expert_flops, (
            grouped_flops, dense_expert_flops
        )
        out, _ = llama._moe_mlp(h, lp, grouped)
        assert out.shape == h.shape
        assert not np.isnan(np.asarray(out)).any()

    def test_decode_shapes_use_scan(self, params):
        """Below the threshold (decode: T = batch) the scan path runs —
        verified by behavior: outputs must be identical regardless of
        capacity_factor (which only affects the grouped path)."""
        lp = jax.tree.map(lambda a: a[0], params["moe_layers"])
        h = jax.random.normal(
            jax.random.PRNGKey(9), (4, 1, CFG.hidden_size), jnp.float32
        )
        a, _ = llama._moe_mlp(h, lp, self._cfg(capacity_factor=0.01))
        b, _ = llama._moe_mlp(h, lp, self._cfg(capacity_factor=100.0))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sharded_moe_training_step_grouped_dispatch():
    """The grouped capacity dispatch must also compile and train on the
    8-device (dp, sp, tp) mesh — the scatter/gather crosses the sp-sharded
    token axis, so XLA inserts the collectives."""
    from dataclasses import replace

    from opsagent_tpu.parallel.mesh import make_mesh
    from opsagent_tpu.training import TrainConfig, init_train_state, make_train_step

    cfg = replace(
        CFG, moe=replace(CFG.moe, grouped_dispatch_min_tokens=1,
                         capacity_factor=2.0),
    )
    mesh = make_mesh(tp=2, dp=2, sp=2)
    tc = TrainConfig(remat=True)
    params, opt_state = init_train_state(
        cfg, tc, mesh, jax.random.PRNGKey(0), dtype=jnp.float32
    )
    step = make_train_step(cfg, tc, mesh, dtype=jnp.float32)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(1, 500, (4, 32)), jnp.int32)
    mask = jnp.ones((4, 32), jnp.float32)
    params, opt_state, metrics = step(params, opt_state, tokens, mask)
    assert np.isfinite(float(metrics["loss"]))


class TestExpertParallelism:
    """The ep mesh axis (parallel/mesh.py): expert weights and the grouped
    dispatch's per-expert buckets shard over ep, so MoE compute scales out
    across devices (the DeepSeek-V3-class configuration). Results must be
    bit-compatible with the unsharded oracle — ep is a layout, not math."""

    def _forward(self, mesh):
        from opsagent_tpu.parallel.mesh import shard_params

        params = llama.init_params(CFG, jax.random.PRNGKey(0), jnp.float32)
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(1, 500, (2, 32)), jnp.int32
        )
        if mesh is None:
            return llama.forward_full(params, CFG, tokens, dtype=jnp.float32)
        sharded = shard_params(params, llama.param_specs(CFG), mesh)
        with mesh:
            return jax.jit(
                lambda p, t: llama.forward_full(p, CFG, t, dtype=jnp.float32)
            )(sharded, tokens)

    def test_ep2_forward_matches_oracle(self):
        from opsagent_tpu.parallel.mesh import make_mesh

        want = self._forward(None)
        got = self._forward(make_mesh(ep=2, dp=2, tp=2))
        assert jnp.allclose(want, got, atol=1e-4), float(
            jnp.max(jnp.abs(want - got))
        )

    def test_ep4_grouped_dispatch_matches(self):
        """Force the grouped (capacity-bucketed) dispatch under ep=4 — the
        path whose buckets actually shard over the expert axis."""
        from dataclasses import replace

        from opsagent_tpu.parallel.mesh import make_mesh, shard_params

        cfg = replace(CFG, moe=replace(CFG.moe, grouped_dispatch_min_tokens=1))
        params = llama.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
        tokens = jnp.asarray(
            np.random.default_rng(1).integers(1, 500, (2, 32)), jnp.int32
        )
        want = llama.forward_full(params, cfg, tokens, dtype=jnp.float32)
        mesh = make_mesh(ep=4, dp=1, tp=2)
        sharded = shard_params(params, llama.param_specs(cfg), mesh)
        with mesh:
            got = jax.jit(
                lambda p, t: llama.forward_full(p, cfg, t, dtype=jnp.float32)
            )(sharded, tokens)
        assert jnp.allclose(want, got, atol=1e-4), float(
            jnp.max(jnp.abs(want - got))
        )

    def test_ep_training_step_finite(self):
        from opsagent_tpu.parallel.mesh import make_mesh
        from opsagent_tpu.training import (
            TrainConfig,
            init_train_state,
            make_train_step,
        )

        mesh = make_mesh(ep=2, dp=2, tp=2)
        tc = TrainConfig(remat=True)
        params, opt_state = init_train_state(
            CFG, tc, mesh, jax.random.PRNGKey(0), dtype=jnp.float32
        )
        step = make_train_step(CFG, tc, mesh, dtype=jnp.float32)
        tokens = jnp.asarray(
            np.random.default_rng(2).integers(1, 500, (4, 16)), jnp.int32
        )
        _, _, metrics = step(params, opt_state, tokens, jnp.ones((4, 16)))
        assert np.isfinite(float(metrics["loss"]))

    def test_engine_generates_under_ep(self):
        from opsagent_tpu.serving.engine import Engine, EngineConfig

        eng = Engine(EngineConfig(
            model="tiny-moe", dtype=jnp.float32, tp=2, ep=2,
            num_pages=128, page_size=8, max_pages_per_seq=16,
            max_batch_size=2, prefill_buckets=(16,),
        ))
        out = eng.generate([[1, 2, 3, 4], [5, 6, 7]], None)
        assert len(out) == 2 and all(len(t) >= 1 for t in out)

    def test_ep_constrain_pins_layout_under_mesh(self):
        """_ep_constrain must actually apply inside jit under `with mesh:`
        (regression: get_abstract_mesh is empty there, which silently
        turned the constraint into dead code)."""
        from opsagent_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(ep=2, dp=2, tp=2)
        P = jax.sharding.PartitionSpec
        with mesh:
            y = jax.jit(
                lambda x: llama._ep_constrain(x, P("ep", None))
            )(jnp.ones((4, 8)))
        assert "ep" in str(y.sharding.spec)

        # ...and stay a no-op with no mesh context at all.
        z = jax.jit(
            lambda x: llama._ep_constrain(x, P("ep", None))
        )(jnp.ones((4, 8)))
        assert "ep" not in str(z.sharding)


def test_sigmoid_router_with_bias_and_groups():
    """DeepSeek-V3 routing semantics (noaux_tc): sigmoid scores, the
    e_score_correction_bias steers SELECTION only (combine weights use
    raw sigmoid scores), and group-limited top-k confines selection to
    the best topk_group expert groups."""
    import dataclasses

    import numpy as np

    from opsagent_tpu.models import llama
    from opsagent_tpu.models.config import MoEConfig, get_config_preset

    base = get_config_preset("tiny-moe")
    cfg = dataclasses.replace(
        base,
        moe=MoEConfig(
            num_experts=4,
            num_experts_per_token=2,
            num_shared_experts=0,
            expert_intermediate_size=8,
            scoring_func="sigmoid",
            n_group=2,
            topk_group=1,
            grouped_dispatch_min_tokens=7777,  # force all-experts scan
        ),
    )
    d, fe, E = cfg.hidden_size, 8, 4
    rng = np.random.default_rng(0)
    h = jnp.asarray(rng.standard_normal((1, 3, d)), jnp.float32)
    lp = {
        # Router logits biased so experts 0 and 2 (in DIFFERENT groups)
        # score highest pre-bias.
        "router": jnp.asarray(
            np.stack([
                np.full((d,), 0.05), np.full((d,), -0.05),
                np.full((d,), 0.04), np.full((d,), -0.04),
            ], axis=1), jnp.float32,
        ),
        "router_bias": jnp.zeros((E,), jnp.float32),
        "eg": jnp.asarray(rng.standard_normal((E, d, fe)) * 0.1, jnp.float32),
        "eu": jnp.asarray(rng.standard_normal((E, d, fe)) * 0.1, jnp.float32),
        "ed": jnp.asarray(rng.standard_normal((E, fe, d)) * 0.1, jnp.float32),
    }
    out_nobias, _ = llama._moe_mlp(h, lp, cfg)

    # A large selection bias on group 1's experts (ids 2,3) must flip the
    # chosen GROUP — changing the output — while zero bias keeps it.
    lp_biased = dict(lp, router_bias=jnp.asarray(
        [0.0, 0.0, 50.0, 50.0], jnp.float32
    ))
    out_biased, _ = llama._moe_mlp(h, lp_biased, cfg)
    assert not np.allclose(np.asarray(out_nobias), np.asarray(out_biased))

    # Bias steers selection only: with selection UNCHANGED (bias uniform
    # across experts), outputs are identical — combine weights ignore it.
    lp_uniform = dict(lp, router_bias=jnp.full((E,), 7.0, jnp.float32))
    out_uniform, _ = llama._moe_mlp(h, lp_uniform, cfg)
    np.testing.assert_allclose(
        np.asarray(out_nobias), np.asarray(out_uniform), rtol=1e-6, atol=1e-6
    )
