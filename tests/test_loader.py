"""Checkpoint loader roundtrip tests (HF safetensors naming)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from opsagent_tpu.models import llama
from opsagent_tpu.models.config import TINY_TEST, ModelConfig
from opsagent_tpu.models.loader import (
    CheckpointError,
    load_checkpoint,
    save_checkpoint,
)


def test_save_load_roundtrip(tmp_path):
    params = llama.init_params(TINY_TEST, jax.random.PRNGKey(0), dtype=jnp.float32)
    ckpt = tmp_path / "model.safetensors"
    save_checkpoint(str(ckpt), params)
    loaded = load_checkpoint(str(ckpt), TINY_TEST, dtype=jnp.float32)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6),
        params,
        loaded,
    )
    # Loaded weights must produce identical logits.
    tokens = jnp.array([[1, 2, 3, 4]], jnp.int32)
    l1 = llama.forward_full(params, TINY_TEST, tokens, dtype=jnp.float32)
    l2 = llama.forward_full(loaded, TINY_TEST, tokens, dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l2), atol=1e-5)


def test_load_with_attn_bias(tmp_path):
    cfg = ModelConfig(
        name="tiny-qwen", vocab_size=128, hidden_size=32, intermediate_size=64,
        num_layers=2, num_heads=4, num_kv_heads=2, attn_bias=True,
        rope_theta=10000.0,
    )
    params = llama.init_params(cfg, jax.random.PRNGKey(1), dtype=jnp.float32)
    ckpt = tmp_path / "model.safetensors"
    save_checkpoint(str(ckpt), params)
    loaded = load_checkpoint(str(ckpt), cfg, dtype=jnp.float32)
    assert "bq" in loaded["layers"]
    np.testing.assert_allclose(
        np.asarray(loaded["layers"]["bq"]), np.asarray(params["layers"]["bq"]), atol=1e-6
    )


def test_shape_mismatch_rejected(tmp_path):
    params = llama.init_params(TINY_TEST, jax.random.PRNGKey(0), dtype=jnp.float32)
    ckpt = tmp_path / "model.safetensors"
    save_checkpoint(str(ckpt), params)
    wrong = ModelConfig(
        name="wrong", vocab_size=1024, hidden_size=64, intermediate_size=128,
        num_layers=2, num_heads=4, num_kv_heads=2,
    )
    with pytest.raises(CheckpointError, match="does not match"):
        load_checkpoint(str(ckpt), wrong, dtype=jnp.float32)


def test_missing_dir(tmp_path):
    with pytest.raises((CheckpointError, FileNotFoundError)):
        load_checkpoint(str(tmp_path / "nope"), TINY_TEST)
