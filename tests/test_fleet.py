"""Fleet serving subsystem (serving/fleet): replica registry semantics,
prefix-affinity routing, session migration over KV-page transfer,
graceful drain with zero token loss, the router's HTTP surface, the
engine server's fleet endpoints, and the CI gates (slo-check /
perf-check) against a router.

The acceptance gates (ISSUE 7): a 2-replica CPU fleet where (a) a
session's second turn routes by prefix-affinity and the owning replica
restores instead of re-prefilling; (b) a forced mis-route ships the KV
pages replica-to-replica and the restored session's greedy tokens are
byte-identical to the single-replica run; (c) graceful drain migrates
every running session with zero request errors and outputs identical to
the never-drained run.
"""

import asyncio
import base64
import json
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from opsagent_tpu import obs
from opsagent_tpu.serving.api import ServingStack, build_engine_app
from opsagent_tpu.serving.engine import Engine, EngineConfig
from opsagent_tpu.serving.fleet.registry import (
    ReplicaInfo,
    ReplicaRegistry,
    prompt_chain_keys,
)
from opsagent_tpu.serving.fleet.router import (
    FleetRouter,
    build_router_app,
)
from opsagent_tpu.serving.fleet.transfer import (
    pack_entries,
    unpack_entries,
)
from opsagent_tpu.serving.offload.pool import HostPagePool, chain_key_hex
from opsagent_tpu.serving.sampler import SamplingParams
from opsagent_tpu.serving.scheduler import Request, RequestError

BASE = dict(
    model="tiny-test", dtype=jnp.float32, tp=1, page_size=4,
    num_pages=256, max_pages_per_seq=64, max_batch_size=4,
    prefill_buckets=(16, 32, 64), decode_block=4, seed=0,
    offload=True,
)


def run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def _fleet(n=2):
    """(router, stacks): n in-process replicas behind a FleetRouter."""
    router = FleetRouter()
    stacks = []
    for i in range(n):
        stack = ServingStack(Engine(EngineConfig(**BASE)))
        stacks.append(stack)
        router.add_local(stack, f"r{i}")
    return router, stacks


def _close(stacks):
    for s in stacks:
        s.close()


# -- registry -----------------------------------------------------------------
class TestRegistry:
    def test_register_heartbeat_reap(self):
        reg = ReplicaRegistry(ttl_s=0.2)
        reg.register(ReplicaInfo(replica_id="a", url="http://x"))
        reg.register(ReplicaInfo(replica_id="b", local=True))
        assert {i.replica_id for i in reg.alive()} == {"a", "b"}
        assert reg.heartbeat("a", load={"running": 3})
        assert not reg.heartbeat("ghost")
        time.sleep(0.3)
        # a went silent past the TTL and is reaped; the local replica is
        # polled live and never reaped.
        assert [i.replica_id for i in reg.alive()] == ["b"]
        assert reg.reaped == 1
        assert reg.get("a") is None

    def test_draining_replicas_stop_admitting(self):
        reg = ReplicaRegistry()
        reg.register(ReplicaInfo(replica_id="a", local=True))
        reg.register(ReplicaInfo(replica_id="b", local=True))
        assert reg.set_draining("a")
        assert [i.replica_id for i in reg.alive()] == ["b"]
        # Still visible to non-admitting reads (timelines, drain itself).
        assert {i.replica_id for i in reg.alive(admitting=False)} == \
            {"a", "b"}
        assert not reg.set_draining("ghost")

    def test_roles_filter(self):
        reg = ReplicaRegistry()
        reg.register(ReplicaInfo(replica_id="d", local=True))
        reg.register(
            ReplicaInfo(replica_id="p", role="prefill", local=True)
        )
        assert [i.replica_id for i in reg.alive(role="decode")] == ["d"]
        assert [i.replica_id for i in reg.alive(role="prefill")] == ["p"]

    def test_affinity_scoring_longest_prefix_wins(self):
        toks = list(range(100, 121))  # 20 usable tokens -> 5 pages of 4
        keys = prompt_chain_keys(toks, page_size=4)
        assert len(keys) == 5
        assert keys[0] == chain_key_hex(toks[:4])
        a = ReplicaInfo(replica_id="a", digests=set(keys[:2]))
        b = ReplicaInfo(replica_id="b", digests=set(keys))
        c = ReplicaInfo(replica_id="c", digests=set(keys[1:]))  # gap at 0
        assert a.affinity_pages(keys) == 2
        assert b.affinity_pages(keys) == 5
        assert c.affinity_pages(keys) == 0  # consecutive from page 0 only

    def test_prompt_chain_keys_exclude_last_token(self):
        # 8 tokens usable=7 -> 1 page; a 9th token adds the second page.
        assert len(prompt_chain_keys(list(range(8)), 4)) == 1
        assert len(prompt_chain_keys(list(range(9)), 4)) == 2
        assert prompt_chain_keys([1], 4) == []


# -- transfer wire format -----------------------------------------------------
def test_pack_unpack_round_trip_preserves_bytes():
    pool = HostPagePool(page_size=4, capacity_bytes=1 << 20)
    toks = list(range(500, 512))
    rng = np.random.default_rng(0)
    trees = []
    for i in range(3):
        tree = {
            "k": rng.standard_normal((2, 4, 1, 8)).astype(np.float32),
            "v": rng.standard_normal((2, 4, 1, 8)).astype(np.float32),
        }
        trees.append(tree)
        assert pool.put(toks[: (i + 1) * 4], tree)
    records = pack_entries(pool.entries_for(toks))
    assert len(records) == 3
    # JSON round trip: the records must survive the HTTP wire.
    records = json.loads(json.dumps(records))
    template = {"k": np.zeros((1,)), "v": np.zeros((1,))}
    out = unpack_entries(records, template)
    assert len(out) == 3
    dst = HostPagePool(page_size=4, capacity_bytes=1 << 20)
    for (chain, tree), want in zip(out, trees):
        np.testing.assert_array_equal(tree["k"], want["k"])
        np.testing.assert_array_equal(tree["v"], want["v"])
        assert dst.put(chain, tree)
    # Destination pool serves the chain under the same keys.
    assert len(dst.match(toks)) == 3
    assert set(dst.digests()) == set(pool.digests())


def test_unpack_drops_structure_mismatch():
    pool = HostPagePool(page_size=4, capacity_bytes=1 << 20)
    pool.put([1, 2, 3, 4], {"k": np.zeros((2, 2), np.float32)})
    records = pack_entries(pool.entries_for([1, 2, 3, 4]))
    bad_template = {"k": np.zeros(1), "v": np.zeros(1)}  # 2 leaves != 1
    assert unpack_entries(records, bad_template) == []


def test_unpack_rejects_tampered_payload_by_digest():
    """A bit flipped in transit (proxy truncation, buggy middlebox) must
    not be imported into the receiver's KV pool: every record carries a
    digest over tokens + leaf bytes, checked at import."""
    from opsagent_tpu import obs

    pool = HostPagePool(page_size=4, capacity_bytes=1 << 20)
    tree = {"k": np.arange(8, dtype=np.float32).reshape(2, 4)}
    pool.put([1, 2, 3, 4], tree)
    records = pack_entries(pool.entries_for([1, 2, 3, 4]))
    assert records[0]["digest"]
    blob = bytearray(base64.b64decode(records[0]["leaves"][0]["data"]))
    blob[0] ^= 0xFF
    records[0]["leaves"][0]["data"] = base64.b64encode(bytes(blob)).decode()
    assert unpack_entries(records, tree) == []
    assert obs.FLEET_KV_IMPORT_REJECTS.value() == 1
    rejects = [
        e for e in obs.flight.get_recorder().snapshot(kind="anomaly")
        if e.get("reason") == "kv_import_reject"
    ]
    assert rejects and rejects[-1]["cause"] == "digest_mismatch"


def test_export_racing_lru_eviction_yields_clean_miss_never_torn():
    """Fleet-global KV race: a chain can be LRU-evicted between the
    directory lookup and the /fleet/kv/export pack. The export side
    (pool.match under the pool lock -> pack_entries over immutable
    entries) must yield either a complete digest-valid chain prefix or
    a clean miss — never an exception or a torn/gappy record set."""
    tree_bytes = 2 * 2 * 4 * 1 * 8 * 4  # k+v, float32
    # Capacity for exactly one 3-page chain: inserting the other chain
    # evicts the first page-by-page, so the reader constantly races.
    pool = HostPagePool(page_size=4, capacity_bytes=3 * tree_bytes)
    toks_a = list(range(100, 112))
    toks_b = list(range(200, 212))
    rng = np.random.default_rng(0)

    def _tree():
        return {
            "k": rng.standard_normal((2, 4, 1, 8)).astype(np.float32),
            "v": rng.standard_normal((2, 4, 1, 8)).astype(np.float32),
        }

    trees_a = [_tree() for _ in range(3)]
    trees_b = [_tree() for _ in range(3)]
    stop = threading.Event()
    writer_errors = []

    def writer():
        try:
            while not stop.is_set():
                for toks, trees in ((toks_a, trees_a), (toks_b, trees_b)):
                    for p in range(3):
                        pool.put(toks[: (p + 1) * 4], trees[p])
        except Exception as e:  # noqa: BLE001 - surfaced below
            writer_errors.append(e)

    t = threading.Thread(target=writer, daemon=True)
    t.start()
    template = {"k": np.zeros((1,)), "v": np.zeros((1,))}
    try:
        for _ in range(300):
            records = pack_entries(pool.match(toks_a))
            out = unpack_entries(records, template)
            # Every packed record digest-verifies (no torn payloads)...
            assert len(out) == len(records)
            # ...and the set is a contiguous chain prefix from page 0.
            for j, (chain, _) in enumerate(out):
                assert list(chain) == toks_a[: (j + 1) * 4]
    finally:
        stop.set()
        t.join(timeout=10)
    assert not writer_errors


def test_unpack_accepts_legacy_records_without_digest():
    """Records from a pre-digest sender (rolling fleet upgrade) still
    import; digest checking is enforced only when the field is present."""
    pool = HostPagePool(page_size=4, capacity_bytes=1 << 20)
    tree = {"k": np.arange(8, dtype=np.float32).reshape(2, 4)}
    pool.put([1, 2, 3, 4], tree)
    records = pack_entries(pool.entries_for([1, 2, 3, 4]))
    for r in records:
        r.pop("digest", None)
    out = unpack_entries(records, tree)
    assert len(out) == 1
    np.testing.assert_array_equal(out[0][1]["k"], tree["k"])


# -- acceptance (a): prefix-affinity routing restores on the owner ------------
def test_second_turn_routes_by_affinity_and_owner_restores():
    router, stacks = _fleet(2)
    try:
        messages = [
            {"role": "system", "content": "fleet affinity test"},
            {"role": "user", "content": "turn one of this session"},
        ]
        resp = router.complete(
            {"messages": messages, "max_tokens": 8, "temperature": 0}
        )
        owner_id = resp["fleet"]["replica"]
        owner = router.registry.get(owner_id).handle
        other = next(
            i.handle for i in router.registry.all()
            if i.replica_id != owner_id
        )
        messages.append({
            "role": "assistant",
            "content": resp["choices"][0]["message"]["content"] or "",
        })
        # Tool window: the session parks its KV to the owner's host pool.
        parked = owner.park_tokens(owner.tokenize({"messages": messages}))
        assert parked > 0
        assert owner.stack.engine.offload.pool.num_pages > 0
        # Simulate a router restart: the sticky pin is gone, so ONLY the
        # prefix digests can route the follow-up turn home.
        router._pins.clear()
        messages.append({"role": "user", "content": "and turn two"})
        own0 = owner.stack.engine.offload.restored_tokens
        oth0 = other.stack.engine.offload.restored_tokens
        resp2 = router.complete(
            {"messages": messages, "max_tokens": 6, "temperature": 0}
        )
        assert resp2["fleet"]["replica"] == owner_id
        assert resp2["fleet"]["policy"] == "affinity"
        # reprefill_avoided > 0 ON THE OWNING REPLICA, nothing elsewhere.
        assert owner.stack.engine.offload.restored_tokens > own0
        assert other.stack.engine.offload.restored_tokens == oth0
        # The decision is on the flight ring with its affinity score.
        decisions = obs.flight.get_recorder().snapshot(
            kind="route_decision"
        )
        assert any(
            d.get("policy") == "affinity" and d.get("affinity_pages", 0) > 0
            and d.get("replica") == owner_id
            for d in decisions
        )
    finally:
        _close(stacks)


# -- acceptance (b): forced mis-route -> KV transfer, identical greedy --------
def test_forced_misroute_transfers_pages_and_matches_single_replica():
    # Reference: the same two turns against ONE replica, never migrated.
    ref_stack = ServingStack(Engine(EngineConfig(**BASE)))
    try:
        messages = [
            {"role": "system", "content": "migration test"},
            {"role": "user", "content": "first turn here"},
        ]
        r1 = ref_stack.chat_completion(
            {"messages": messages, "max_tokens": 8, "temperature": 0}
        )
        turn1_text = r1["choices"][0]["message"]["content"] or ""
        ref_messages = list(messages) + [
            {"role": "assistant", "content": turn1_text},
            {"role": "user", "content": "second turn now"},
        ]
        r2 = ref_stack.chat_completion(
            {"messages": ref_messages, "max_tokens": 8, "temperature": 0}
        )
        want_turn2 = r2["choices"][0]["message"]["content"] or ""
    finally:
        ref_stack.close()

    # pagestore=False pins the LEGACY eager-push path: with the fleet
    # page directory on, a misroute pulls via peer fault-in instead
    # (tests/test_pagestore.py covers that side).
    router = FleetRouter(pagestore=False)
    stacks = []
    for i in range(2):
        stack = ServingStack(Engine(EngineConfig(**BASE)))
        stacks.append(stack)
        router.add_local(stack, f"r{i}")
    try:
        resp = router.complete(
            {"messages": messages, "max_tokens": 8, "temperature": 0}
        )
        owner_id = resp["fleet"]["replica"]
        assert (resp["choices"][0]["message"]["content"] or "") == \
            turn1_text
        owner = router.registry.get(owner_id).handle
        target_id = next(
            i.replica_id for i in router.registry.all()
            if i.replica_id != owner_id
        )
        target = router.registry.get(target_id).handle
        fleet_messages = list(messages) + [
            {"role": "assistant", "content": turn1_text},
            {"role": "user", "content": "second turn now"},
        ]
        # Park (the tool window) so the chain is host-pool resident on
        # the owner, then FORCE the follow-up onto the other replica.
        owner.park_tokens(
            owner.tokenize({"messages": fleet_messages})
        )
        t0 = obs.metrics_snapshot().get(
            "opsagent_fleet_kv_transfer_pages_total", 0.0
        )
        tgt0 = target.stack.engine.offload.restored_tokens
        resp2 = router.complete(
            {"messages": fleet_messages, "max_tokens": 8,
             "temperature": 0},
            force_replica=target_id,
        )
        assert resp2["fleet"]["replica"] == target_id
        # The mis-route triggered a replica-to-replica page transfer...
        assert obs.metrics_snapshot().get(
            "opsagent_fleet_kv_transfer_pages_total", 0.0
        ) > t0
        migrations = obs.flight.get_recorder().snapshot(
            kind="session_migrate"
        )
        assert any(
            m.get("phase") == "enter" and m.get("reason") == "misroute"
            for m in migrations
        )
        assert any(
            m.get("phase") == "exit" and m.get("pages", 0) > 0
            for m in migrations
        )
        # ...the receiving engine restored instead of re-prefilling...
        assert target.stack.engine.offload.restored_tokens > tgt0
        # ...and the restored session's greedy output is byte-identical
        # to the single-replica run.
        assert (resp2["choices"][0]["message"]["content"] or "") == \
            want_turn2
    finally:
        _close(stacks)


# -- acceptance (c) + satellite: graceful drain, zero loss --------------------
def test_graceful_drain_migrates_running_sessions_without_token_loss():
    """_requeue_salvaged under drain: a drained replica's parked
    sessions re-enter another replica's queue with their generated
    tokens salvaged — greedy outputs identical to the never-drained run,
    zero request errors."""
    prompt = [257, 3, 1, 4, 1, 5, 9, 2, 6]
    budget = 24
    ref = Engine(EngineConfig(**BASE))
    want = ref.generate([prompt], SamplingParams(max_tokens=budget))[0]

    router, stacks = _fleet(2)
    try:
        req = Request(list(prompt), SamplingParams(max_tokens=budget))
        stacks[0].scheduler.submit(req)
        deadline = time.time() + 30
        while time.time() < deadline:
            if req.seq_id is not None and \
                    req.seq_id in stacks[0].scheduler._running:
                break
            time.sleep(0.01)
        time.sleep(0.2)  # let it decode some tokens mid-flight
        b0 = stacks[1].engine.offload.restored_tokens
        out = router.drain("r0")
        assert out["errors"] == 0
        assert out["migrated_sessions"] == 1
        assert req.done.wait(60), "request lost by the drain"
        assert not req.error
        assert req.tokens == want, (req.tokens, want)
        # The salvage re-admitted with tokens generated pre-drain folded
        # into the prompt (no token was re-generated or lost)...
        assert req.generated_prefix, "drain salvaged nothing"
        # ...restoring the KV pages shipped from the drained replica.
        assert stacks[1].engine.offload.restored_tokens > b0
        # The drained replica left the fleet; new traffic routes to r1.
        assert router.registry.get("r0") is None
        resp = router.complete({
            "messages": [{"role": "user", "content": "post-drain"}],
            "max_tokens": 4, "temperature": 0,
        })
        assert resp["fleet"]["replica"] == "r1"
        drains = obs.flight.get_recorder().snapshot(kind="replica_drain")
        assert any(
            d.get("phase") == "exit" and d.get("migrated") == 1
            and d.get("errors") == 0 for d in drains
        )
    finally:
        _close(stacks)


def test_drain_without_offload_still_loses_no_tokens():
    """Engines without the offload tier drain correctly too: the salvage
    folds into the prompt and the target re-prefills (slower, same
    tokens)."""
    kw = dict(BASE, offload=False)
    prompt = [257, 8, 6, 7, 5, 3, 0, 9]
    ref = Engine(EngineConfig(**kw))
    want = ref.generate([prompt], SamplingParams(max_tokens=16))[0]
    router = FleetRouter()
    stacks = [ServingStack(Engine(EngineConfig(**kw))) for _ in range(2)]
    router.add_local(stacks[0], "a")
    router.add_local(stacks[1], "b")
    try:
        req = Request(list(prompt), SamplingParams(max_tokens=16))
        stacks[0].scheduler.submit(req)
        deadline = time.time() + 30
        while time.time() < deadline and not req.tokens:
            time.sleep(0.01)
        out = router.drain("a")
        assert out["errors"] == 0
        assert req.done.wait(60) and not req.error
        assert req.tokens == want
    finally:
        _close(stacks)


# -- spill-over + sessionless fallbacks ---------------------------------------
def test_queue_spill_bounces_pinned_replica():
    router, stacks = _fleet(2)
    try:
        body = {
            "messages": [{"role": "user", "content": "spill session"}],
            "max_tokens": 4, "temperature": 0,
        }
        resp = router.complete(body)
        owner_id = resp["fleet"]["replica"]
        # Saturate the pinned replica's queue past the spill bound.
        router.queue_spill = 1
        info = router.registry.get(owner_id)
        info.load = dict(info.load, queued=5, prefilling=0)
        # refresh_local would overwrite the fake depth; freeze it.
        router.registry.refresh_local = lambda: None
        d = router.route(body, router.tokenize(body))
        assert d.policy == "spill"
        assert d.replica.replica_id != owner_id
        assert obs.metrics_snapshot().get(
            "opsagent_fleet_queue_spillovers_total", 0.0
        ) >= 1
    finally:
        _close(stacks)


def test_no_replicas_is_503():
    router = FleetRouter()
    with pytest.raises(RequestError) as ei:
        router.complete({
            "messages": [{"role": "user", "content": "x"}],
        })
    assert ei.value.status == 503


def test_round_robin_placement_rotates():
    router = FleetRouter(placement="round_robin", sticky=False,
                         affinity=False)
    reg = router.registry
    reg.register(ReplicaInfo(replica_id="a", local=True))
    reg.register(ReplicaInfo(replica_id="b", local=True))
    body = {"messages": [{"role": "user", "content": "x"}]}
    picks = [router.route(body).replica.replica_id for _ in range(4)]
    assert picks == ["a", "b", "a", "b"]


# -- router HTTP surface ------------------------------------------------------
def test_router_http_endpoints_round_trip():
    router, stacks = _fleet(2)
    app = build_router_app(router)

    async def scenario():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.get("/healthz")
            assert r.status == 200
            h = await r.json()
            assert h["role"] == "router" and h["replicas"] == 2

            r = await client.post("/v1/chat/completions", json={
                "messages": [{"role": "user", "content": "via router"}],
                "max_tokens": 4, "temperature": 0,
            })
            assert r.status == 200, await r.text()
            body = await r.json()
            assert body["choices"][0]["message"] is not None
            assert body["fleet"]["replica"] in ("r0", "r1")
            rid = body["id"]

            # Satellite: request-id pass-through — the router forwards
            # the timeline to the owning replica instead of 404ing.
            r = await client.get(f"/api/timeline/{rid}")
            assert r.status == 200, await r.text()
            tl = await r.json()
            assert tl["replica"] == body["fleet"]["replica"]
            r = await client.get("/api/timeline/nope-123")
            assert r.status == 404

            r = await client.get("/api/fleet")
            assert r.status == 200
            fleet = await r.json()
            assert len(fleet["replicas"]) == 2
            assert all("slo" in row for row in fleet["replicas"])
            assert fleet["pinned_sessions"] >= 1

            r = await client.get("/api/slo")
            assert r.status == 200
            slo = await r.json()
            assert slo["fleet"]["replicas"] == 2
            names = {v["name"] for v in slo["slos"]}
            assert any(n.startswith("r0:") for n in names)
            assert any(n.startswith("r1:") for n in names)

            r = await client.get("/api/fleet/bench")
            assert r.status == 200
            rows = await r.json()
            assert rows and all(
                "metric" in row and "value" in row for row in rows
            )

            r = await client.get("/v1/models")
            models = await r.json()
            assert models["data"][0]["id"] == "tiny-test"

            r = await client.get("/metrics")
            text = await r.text()
            assert "opsagent_fleet_route_decisions_total" in text

            # Streaming through the router.
            r = await client.post("/v1/chat/completions", json={
                "messages": [{"role": "user", "content": "stream me"}],
                "max_tokens": 4, "temperature": 0, "stream": True,
            })
            assert r.status == 200
            sse = await r.text()
            assert "data: [DONE]" in sse

            # HTTP registration + heartbeat + 410 after deregister.
            r = await client.post("/fleet/register", json={
                "replica_id": "remote-1",
                "url": "http://127.0.0.1:1",
                "model": "tiny-test", "capacity": 2, "page_size": 4,
            })
            assert r.status == 200
            r = await client.post("/fleet/heartbeat", json={
                "replica_id": "remote-1", "load": {"running": 1},
            })
            assert r.status == 200
            r = await client.post("/fleet/deregister", json={
                "replica_id": "remote-1",
            })
            assert r.status == 200
            r = await client.post("/fleet/heartbeat", json={
                "replica_id": "remote-1",
            })
            assert r.status == 410

            # Drain over HTTP (no live sessions: clean deregistration).
            r = await client.post("/fleet/drain/r1")
            assert r.status == 200
            out = await r.json()
            assert out["errors"] == 0
            r = await client.post("/fleet/drain/ghost")
            assert r.status == 404
        finally:
            await client.close()

    try:
        run(scenario())
    finally:
        _close(stacks)


# -- engine server fleet surface ----------------------------------------------
def test_engine_server_fleet_endpoints_and_healthz_block():
    from opsagent_tpu.serving.fleet.client import FleetMembership

    stack_a = ServingStack(Engine(EngineConfig(**BASE)))
    stack_b = ServingStack(Engine(EngineConfig(**BASE)))
    membership = FleetMembership(
        stack_a, router_url="http://127.0.0.1:1",
        advertise_url="http://127.0.0.1:2", replica_id="rep-a",
        role="decode",
    )
    app_a = build_engine_app(stack_a, membership=membership)
    app_b = build_engine_app(stack_b)

    async def scenario():
        ca = TestClient(TestServer(app_a))
        cb = TestClient(TestServer(app_b))
        await ca.start_server()
        await cb.start_server()
        try:
            # Satellite: /healthz gains the fleet block.
            r = await ca.get("/healthz")
            h = await r.json()
            assert h["fleet"]["replica_id"] == "rep-a"
            assert h["fleet"]["role"] == "decode"
            assert h["fleet"]["router_url"] == "http://127.0.0.1:1"
            assert h["fleet"]["draining"] is False
            assert "queued" in h and "prefilling" in h
            # No membership -> no fleet block.
            r = await cb.get("/healthz")
            assert "fleet" not in await r.json()

            # Generate on A so its trie holds a chain, then move it to
            # B purely over the HTTP fleet endpoints.
            r = await ca.post("/v1/chat/completions", json={
                "messages": [{"role": "user", "content": "http fleet"}],
                "max_tokens": 8, "temperature": 0,
            })
            assert r.status == 200, await r.text()

            r = await ca.get("/fleet/digests")
            dig = await r.json()
            assert dig["page_size"] == 4 and dig["digests"]

            from opsagent_tpu.serving.chat_template import (
                apply_chat_template,
            )

            toks = apply_chat_template(
                stack_a.engine.tokenizer,
                [{"role": "user", "content": "http fleet"}],
                model_family="tiny-test",
            )
            r = await ca.post("/fleet/kv/export", json={"tokens": toks})
            assert r.status == 200
            exported = await r.json()
            assert exported["pages"], "nothing exported"

            b0 = stack_b.engine.offload.pool.num_pages
            r = await cb.post(
                "/fleet/kv/import", json={"pages": exported["pages"]}
            )
            imported = await r.json()
            assert imported["imported"] == len(exported["pages"])
            assert stack_b.engine.offload.pool.num_pages == \
                b0 + imported["imported"]

            # /fleet/park round trip + bad input.
            r = await ca.post("/fleet/park", json={"tokens": "nope"})
            assert r.status == 400
            r = await ca.post("/fleet/park", json={"tokens": toks})
            assert r.status == 200

            # Drain notification flips the healthz block.
            r = await ca.post("/fleet/drain")
            assert (await r.json())["status"] == "draining"
            r = await ca.get("/healthz")
            assert (await r.json())["fleet"]["draining"] is True
        finally:
            await ca.close()
            await cb.close()

    try:
        run(scenario())
    finally:
        stack_a.close()
        stack_b.close()


# -- CI gates against the router ----------------------------------------------
def _serve_router_on_port(router):
    """Run the router app on a real localhost port (the CLI gates use
    urllib, which cannot talk to aiohttp's TestClient transport).
    Returns (base_url, stop_fn)."""
    app = build_router_app(router)
    loop = asyncio.new_event_loop()
    runner_box = {}

    async def _start():
        from aiohttp import web

        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        runner_box["runner"] = runner
        runner_box["port"] = runner.addresses[0][1]

    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()
    asyncio.run_coroutine_threadsafe(_start(), loop).result(timeout=30)

    def stop():
        async def _stop():
            await runner_box["runner"].cleanup()

        asyncio.run_coroutine_threadsafe(_stop(), loop).result(timeout=30)
        loop.call_soon_threadsafe(loop.stop)
        t.join(timeout=10)

    return f"http://127.0.0.1:{runner_box['port']}", stop


def test_slo_check_and_perf_check_gate_a_running_fleet(
    tmp_path, capsys, monkeypatch
):
    from opsagent_tpu.cli.perfcheck import run_perf_check
    from opsagent_tpu.cli.slocheck import run_slo_check

    # The unwarmed CPU engines pay their first compile inside TTFT;
    # loosen the declared target so the gate's verdict is deterministic
    # (this test is about the ROUTER plumbing, not the latency).
    monkeypatch.setenv("OPSAGENT_SLO_TTFT_MS", "60000")
    router, stacks = _fleet(2)
    url, stop = _serve_router_on_port(router)
    try:
        # Drive one request so the SLO histograms carry data.
        router.complete({
            "messages": [{"role": "user", "content": "gate me"}],
            "max_tokens": 4, "temperature": 0,
        })
        assert run_slo_check(url=url) == 0
        out = capsys.readouterr().out
        assert "fleet rollup over 2 replica(s)" in out
        assert "r0:" in out and "r1:" in out

        # perf-check --url: live fleet rows vs a baseline built from
        # those same rows (pass), then vs a much-better baseline (fail).
        from opsagent_tpu.cli.perfcheck import fetch_rows

        rows = fetch_rows(url)
        assert rows
        base = tmp_path / "baseline.jsonl"
        with open(base, "w") as f:
            for row in rows:
                f.write(json.dumps(row) + "\n")
        assert run_perf_check(url, baseline=str(base)) == 0
        fast = []
        for row in rows:
            fast.append(dict(row, value=row["value"] / 100.0)
                        if row["unit"] == "ms" else row)
        with open(base, "w") as f:
            for row in fast:
                f.write(json.dumps(row) + "\n")
        assert run_perf_check(url, baseline=str(base)) == 1
    finally:
        stop()
        _close(stacks)


def test_drained_membership_does_not_rejoin_the_fleet():
    """Regression (caught in a live drive): after a router drain
    deregisters a replica, its heartbeat used to get a 410 and
    RE-REGISTER — rejoining the fleet it was just drained from. A
    draining membership must stop registering/heartbeating."""
    import queue as _q

    from opsagent_tpu.serving.fleet.client import FleetMembership

    class _Sched:
        _running: dict = {}
        _waiting: list = []
        _prefilling: dict = {}
        _queue = _q.Queue()

    class _Alloc:
        free_pages = 7

    class _Cfg:
        max_batch_size = 2
        page_size = 4
        tp = sp = ep = 1

    class _Eng:
        cfg = _Cfg()
        alloc = _Alloc()

        def prefix_digests(self):
            return []

    class _Stack:
        engine = _Eng()
        scheduler = _Sched()
        model_name = "tiny-test"

    router = FleetRouter()
    url, stop = _serve_router_on_port(router)
    m = FleetMembership(
        _Stack(), router_url=url, advertise_url="http://127.0.0.1:1",
        replica_id="mem-rep", heartbeat_interval_s=0.05,
    )
    try:
        m.start()
        assert m.registered
        deadline = time.time() + 5
        while router.registry.get("mem-rep") is None and \
                time.time() < deadline:
            time.sleep(0.02)
        assert router.registry.get("mem-rep") is not None
        # Drain: the router deregisters; the engine-side flag flips (the
        # /fleet/drain endpoint does this on a real engine server).
        m.draining = True
        router.drain("mem-rep")
        assert router.registry.get("mem-rep") is None
        time.sleep(0.5)  # ~10 heartbeat intervals
        assert router.registry.get("mem-rep") is None, \
            "drained replica rejoined the fleet"
        block = m.healthz_block()
        assert block["draining"] is True
    finally:
        m.stop(deregister=False)
        stop()


# -- disaggregated prefill lanes ----------------------------------------------
def test_prefill_lane_takes_long_cold_admission_and_hands_off():
    router = FleetRouter(prefill_threshold=32)
    stacks = [ServingStack(Engine(EngineConfig(**BASE)))
              for _ in range(2)]
    router.add_local(stacks[0], "decode-0")
    lane = router.add_local(stacks[1], "lane-0")
    router.registry.get("lane-0").role = "prefill"
    try:
        # A long cold prompt: well past the threshold, no affinity
        # anywhere -> the prefill lane runs it first, the decode replica
        # restores the handed-off pages.
        long_user = "kubectl get pods " * 6  # ~100 byte-tokens >= 32
        d0 = stacks[0].engine.offload.restored_tokens
        resp = router.complete({
            "messages": [{"role": "user", "content": long_user}],
            "max_tokens": 4, "temperature": 0,
        })
        assert resp["fleet"]["replica"] == "decode-0"
        handoffs = [
            m for m in obs.flight.get_recorder().snapshot(
                kind="session_migrate"
            ) if m.get("reason") == "prefill_handoff"
        ]
        assert handoffs, "prefill lane never engaged"
        assert any(m.get("pages", 0) > 0 for m in handoffs
                   if m.get("phase") == "exit")
        assert stacks[0].engine.offload.restored_tokens > d0
        # The lane decision is visible on the metrics + flight ring.
        assert obs.metrics_snapshot().get(
            'opsagent_fleet_route_decisions_total{policy="prefill"}', 0.0
        ) >= 1
        # Short prompts skip the lane.
        n_handoffs = len(handoffs)
        router.complete({
            "messages": [{"role": "user", "content": "hi"}],
            "max_tokens": 2, "temperature": 0,
        })
        assert len([
            m for m in obs.flight.get_recorder().snapshot(
                kind="session_migrate"
            ) if m.get("reason") == "prefill_handoff"
        ]) == n_handoffs
    finally:
        _close(stacks)
        del lane
