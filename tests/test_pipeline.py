"""Pipeline parallelism (parallel/pipeline.py) on the virtual 8-device CPU
mesh: a pp=2 GPipe train step must match the pp=1 oracle exactly — same
loss, same updated parameters — since microbatch pipelining is a pure
re-scheduling of the same math."""

import jax
import jax.numpy as jnp
import pytest

from opsagent_tpu.models.config import get_config_preset
from opsagent_tpu.parallel.mesh import make_mesh
from opsagent_tpu.parallel.pipeline import make_pipeline_loss, param_specs_pp
from opsagent_tpu.training import (
    TrainConfig,
    init_train_state,
    make_train_step,
)

CFG = get_config_preset("tiny-test")  # 2 dense layers -> 1 per stage at pp=2


def _data(B=4, S=16):
    tokens = jnp.asarray(
        jax.random.randint(jax.random.PRNGKey(1), (B, S), 0, CFG.vocab_size),
        jnp.int32,
    )
    mask = jnp.ones((B, S), jnp.float32)
    return tokens, mask


def test_pp2_train_step_matches_pp1_oracle():
    tc = TrainConfig(
        learning_rate=1e-3, remat=False, pp_microbatches=2
    )
    tokens, mask = _data()

    mesh1 = make_mesh(tp=2, dp=2, sp=2)          # pp=1 oracle
    p1, o1 = init_train_state(
        CFG, tc, mesh1, jax.random.PRNGKey(0), dtype=jnp.float32
    )
    step1 = make_train_step(CFG, tc, mesh1, dtype=jnp.float32)
    p1, o1, m1 = step1(p1, o1, tokens, mask)

    mesh2 = make_mesh(pp=2, dp=2, sp=1, tp=2)    # pipelined
    p2, o2 = init_train_state(
        CFG, tc, mesh2, jax.random.PRNGKey(0), dtype=jnp.float32
    )
    step2 = make_train_step(CFG, tc, mesh2, dtype=jnp.float32)
    p2, o2, m2 = step2(p2, o2, tokens, mask)

    assert abs(float(m1["loss"]) - float(m2["loss"])) < 1e-5
    flat1 = jax.tree.leaves(p1)
    flat2 = jax.tree.leaves(p2)
    assert len(flat1) == len(flat2)
    for a, b in zip(flat1, flat2):
        assert jnp.allclose(a, b, atol=1e-4), (a.shape, b.shape)


def test_pp2_training_reduces_loss():
    tc = TrainConfig(learning_rate=3e-3, remat=False, pp_microbatches=2)
    mesh = make_mesh(pp=2, dp=1, sp=1, tp=4)
    params, opt_state = init_train_state(
        CFG, tc, mesh, jax.random.PRNGKey(0), dtype=jnp.float32
    )
    step = make_train_step(CFG, tc, mesh, dtype=jnp.float32)
    tokens, mask = _data()
    losses = []
    for _ in range(4):
        params, opt_state, metrics = step(params, opt_state, tokens, mask)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], losses
    assert all(l == l for l in losses)  # no NaN


def test_pp_specs_stage_layer_axis():
    specs = param_specs_pp(CFG)
    assert specs["layers"]["wq"][0] == "pp"
    assert specs["layers"]["attn_norm"][0] == "pp"
    assert "pp" not in jax.tree.leaves(
        [specs["embed"]], is_leaf=lambda x: True
    )[0]  # embed stays replicated over pp


def test_pp_rejects_bad_divisibility():
    # tiny-moe has ONE MoE layer: not divisible over pp=2.
    mesh = make_mesh(pp=2, dp=1, sp=1, tp=4)
    moe_cfg = get_config_preset("tiny-moe")
    with pytest.raises(ValueError, match="divisible"):
        make_pipeline_loss(moe_cfg, mesh, 2, dtype=jnp.float32)
    mesh3 = make_mesh(pp=8, dp=1, sp=1, tp=1)
    with pytest.raises(ValueError, match="divisible"):
        make_pipeline_loss(CFG, mesh3, 2, dtype=jnp.float32)


def test_pp_remat_matches():
    """jax.checkpoint on the stage body must not change pipeline results."""
    tokens, mask = _data()
    mesh = make_mesh(pp=2, dp=1, sp=1, tp=4)
    vals = []
    for remat in (False, True):
        loss_fn = make_pipeline_loss(
            CFG, mesh, 2, dtype=jnp.float32, remat=remat
        )
        from opsagent_tpu.models import llama
        from opsagent_tpu.parallel.mesh import shard_params

        params = shard_params(
            llama.init_params(CFG, jax.random.PRNGKey(0), jnp.float32),
            param_specs_pp(CFG), mesh,
        )
        with mesh:
            loss, _ = jax.jit(loss_fn)(params, tokens, mask)
        vals.append(float(loss))
    assert abs(vals[0] - vals[1]) < 1e-5


MOE_CFG = __import__("dataclasses").replace(
    get_config_preset("tiny-moe"), num_layers=3
)  # 1 dense prefix + 2 MoE layers -> 1 MoE layer per stage at pp=2


def _moe_data(B=4, S=16):
    tokens = jnp.asarray(
        jax.random.randint(
            jax.random.PRNGKey(1), (B, S), 0, MOE_CFG.vocab_size
        ),
        jnp.int32,
    )
    return tokens, jnp.ones((B, S), jnp.float32)


def test_pp2_moe_matches_pp1_oracle():
    """MoE under pipeline parallelism (dense prefix on stage 0, MoE stack
    pp-staged): with the aux regularizer off, GPipe is a pure
    re-scheduling — loss and updated params must match the pp=1 oracle."""
    tc = TrainConfig(
        learning_rate=1e-3, remat=False, pp_microbatches=2,
        moe_aux_weight=0.0,
    )
    tokens, mask = _moe_data()

    mesh1 = make_mesh(tp=4, dp=2, sp=1)          # pp=1 oracle
    p1, o1 = init_train_state(
        MOE_CFG, tc, mesh1, jax.random.PRNGKey(0), dtype=jnp.float32
    )
    step1 = make_train_step(MOE_CFG, tc, mesh1, dtype=jnp.float32)
    p1, o1, m1 = step1(p1, o1, tokens, mask)

    mesh2 = make_mesh(pp=2, dp=2, sp=1, tp=2)    # pipelined
    p2, o2 = init_train_state(
        MOE_CFG, tc, mesh2, jax.random.PRNGKey(0), dtype=jnp.float32
    )
    step2 = make_train_step(MOE_CFG, tc, mesh2, dtype=jnp.float32)
    p2, o2, m2 = step2(p2, o2, tokens, mask)

    assert abs(float(m1["loss"]) - float(m2["loss"])) < 1e-5
    assert float(m2["moe_aux"]) > 0.0          # router aux measured
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
        assert jnp.allclose(a, b, atol=1e-4), (a.shape, b.shape)


def test_pp2_moe_with_ep_trains():
    """The full EP x PP x TP composition on one mesh: pipeline stages over
    pp, experts sharded over ep inside each stage, Megatron tp splits —
    the DeepSeek-V3-class layout (VERDICT r2 weak #7). Loss must fall and
    stay finite with the aux regularizer ON."""
    tc = TrainConfig(
        learning_rate=3e-3, remat=True, pp_microbatches=2,
        moe_aux_weight=0.01,
    )
    mesh = make_mesh(pp=2, ep=2, dp=1, sp=1, tp=2)
    params, opt_state = init_train_state(
        MOE_CFG, tc, mesh, jax.random.PRNGKey(0), dtype=jnp.float32
    )
    step = make_train_step(MOE_CFG, tc, mesh, dtype=jnp.float32)
    tokens, mask = _moe_data()
    losses = []
    for _ in range(4):
        params, opt_state, metrics = step(params, opt_state, tokens, mask)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], losses
    assert all(l == l for l in losses)


def test_pp_moe_specs_stage_layer_axis():
    specs = param_specs_pp(MOE_CFG)
    assert specs["moe_layers"]["eg"][0] == "pp"
    assert specs["moe_layers"]["eg"][1] == "ep"   # ep preserved inside stage
    assert specs["layers"]["wq"][0] is None or "pp" not in str(
        specs["layers"]["wq"][0]
    )  # dense prefix replicated over pp


def test_pp2_sp2_ring_matches_pp1_oracle():
    """pp x sp composition (VERDICT r03 missing #3): a pp=2 x sp=2 mesh —
    ring attention over the sp axis INSIDE each pipeline stage — must
    match the unpipelined unsharded oracle exactly: pipelining is a
    re-scheduling and the ring is a re-layout of the same math, including
    the next-token shift across the sp shard boundary."""
    tc = TrainConfig(
        learning_rate=1e-3, remat=False, pp_microbatches=2,
        ring_attention=True,
    )
    tokens, mask = _data(B=2, S=32)
    # Mask out a few positions so the cross-boundary mask shift is
    # exercised with a non-trivial pattern.
    mask = mask.at[:, :3].set(0.0)

    mesh1 = make_mesh(tp=2, dp=1, sp=1)          # plain oracle
    p1, o1 = init_train_state(
        CFG, tc, mesh1, jax.random.PRNGKey(0), dtype=jnp.float32
    )
    step1 = make_train_step(CFG, tc, mesh1, dtype=jnp.float32)
    p1, o1, m1 = step1(p1, o1, tokens, mask)

    mesh2 = make_mesh(pp=2, dp=1, sp=2, tp=2)    # pipelined + ring
    p2, o2 = init_train_state(
        CFG, tc, mesh2, jax.random.PRNGKey(0), dtype=jnp.float32
    )
    step2 = make_train_step(CFG, tc, mesh2, dtype=jnp.float32)
    p2, o2, m2 = step2(p2, o2, tokens, mask)

    assert abs(float(m1["loss"]) - float(m2["loss"])) < 1e-5
    import numpy as np

    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
        # device_get first: the two meshes span different device sets.
        assert np.allclose(
            jax.device_get(a), jax.device_get(b), atol=1e-4
        ), (a.shape, b.shape)


def test_pp2_sp2_dp2_composes():
    """Full pp x dp x sp x tp mesh (8 virtual devices, every axis real):
    the step executes and produces a finite loss.

    Runs in a FRESH subprocess: this is the only program whose manual
    ppermute spans all 8 virtual devices (pp2 x dp2 x sp2), and XLA:CPU's
    collective-permute rendezvous has a thread-race CHECK
    (rendezvous.h:315 "id < num_threads (8 vs. 8)") that fires when the
    host's thread pools were oversubscribed by earlier in-process work
    (e.g. a serving engine built by a previous test). The race is in the
    CPU runtime's rendezvous bookkeeping, not in the sharded program —
    the same program is deterministic standalone and TPU executes
    ppermute on ICI without this code path."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    flags = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    )
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip(),
        "PYTHONPATH": repo + os.pathsep + env.get("PYTHONPATH", ""),
    })
    child = (
        "import jax, jax.numpy as jnp\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from opsagent_tpu.models.config import get_config_preset\n"
        "from opsagent_tpu.parallel.mesh import make_mesh\n"
        "from opsagent_tpu.training import (TrainConfig, init_train_state,"
        " make_train_step)\n"
        "cfg = get_config_preset('tiny-test')\n"
        "tc = TrainConfig(learning_rate=1e-3, remat=True,"
        " pp_microbatches=2, ring_attention=True)\n"
        "tokens = jnp.asarray(jax.random.randint(jax.random.PRNGKey(1),"
        " (4, 32), 0, cfg.vocab_size), jnp.int32)\n"
        "mask = jnp.ones((4, 32), jnp.float32)\n"
        "mesh = make_mesh(pp=2, dp=2, sp=2, tp=1)\n"
        "p, o = init_train_state(cfg, tc, mesh, jax.random.PRNGKey(0),"
        " dtype=jnp.float32)\n"
        "step = make_train_step(cfg, tc, mesh, dtype=jnp.float32)\n"
        "p, o, m = step(p, o, tokens, mask)\n"
        "loss = float(m['loss'])\n"
        "assert loss == loss and loss < 1e9, loss\n"
        "print(f'dp2-loss-ok {loss:.4f}')\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", child], capture_output=True, text=True,
        timeout=420, env=env, cwd=repo,
    )
    assert out.returncode == 0, (out.stdout + out.stderr)[-3000:]
    assert "dp2-loss-ok" in out.stdout


def test_pp2_sp2_ep2_moe_matches_pp1_oracle():
    """The full DeepSeek-long-context layout on one mesh: MoE stack
    pipelined over pp, experts sharded over ep, ring attention over sp
    inside each stage. With the aux regularizer off this is still a pure
    re-layout of the same math — loss and updated params must match the
    unsharded pp=1 oracle."""
    tc = TrainConfig(
        learning_rate=1e-3, remat=False, pp_microbatches=2,
        moe_aux_weight=0.0, ring_attention=True,
    )
    tokens, mask = _moe_data(B=2, S=32)
    mask = mask.at[:, :2].set(0.0)  # exercise the cross-shard mask shift

    mesh1 = make_mesh(tp=2, dp=1, sp=1)          # pp=1 oracle
    p1, o1 = init_train_state(
        MOE_CFG, tc, mesh1, jax.random.PRNGKey(0), dtype=jnp.float32
    )
    step1 = make_train_step(MOE_CFG, tc, mesh1, dtype=jnp.float32)
    p1, o1, m1 = step1(p1, o1, tokens, mask)

    mesh2 = make_mesh(pp=2, dp=1, sp=2, ep=2, tp=1)
    p2, o2 = init_train_state(
        MOE_CFG, tc, mesh2, jax.random.PRNGKey(0), dtype=jnp.float32
    )
    step2 = make_train_step(MOE_CFG, tc, mesh2, dtype=jnp.float32)
    p2, o2, m2 = step2(p2, o2, tokens, mask)

    assert abs(float(m1["loss"]) - float(m2["loss"])) < 1e-5
    import numpy as np

    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
        assert np.allclose(
            jax.device_get(a), jax.device_get(b), atol=1e-4
        ), (a.shape, b.shape)
