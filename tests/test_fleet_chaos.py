"""Fleet failure containment (serving/faults + router failover): the
deterministic fault-injection harness, the per-replica circuit breaker
(healthy -> suspect -> ejected -> half-open probe), connect-phase retry
with re-route, mid-SSE failover that resumes the client stream
byte-identically, TTFT hedging, overload shedding (429 + Retry-After),
and the scheduler/agent fault points.

The acceptance gate (ISSUE 9): kill a replica mid-decode in a 2-replica
in-process fleet under a seeded fault spec — every in-flight request
completes on the surviving replica with zero client-visible errors, the
streamed text has no gaps or duplicated tokens at the failover seam, and
the greedy output is byte-identical to a fault-free run.
"""

import asyncio
import json
import threading
import time
import urllib.error
import urllib.request

import jax.numpy as jnp
import pytest

from opsagent_tpu import obs
from opsagent_tpu.serving import faults
from opsagent_tpu.serving.api import ServingStack
from opsagent_tpu.serving.engine import Engine, EngineConfig
from opsagent_tpu.serving.fleet.registry import (
    EJECT_AFTER_FAILURES,
    ReplicaInfo,
    ReplicaRegistry,
)
from opsagent_tpu.serving.fleet.router import (
    FleetRouter,
    OverloadError,
    build_router_app,
)
from opsagent_tpu.serving.sampler import SamplingParams
from opsagent_tpu.serving.scheduler import Request, Scheduler

BASE = dict(
    model="tiny-test", dtype=jnp.float32, tp=1, page_size=4,
    num_pages=256, max_pages_per_seq=64, max_batch_size=4,
    prefill_buckets=(16, 32, 64), decode_block=4, seed=0,
    offload=True,
)


def _fleet(n=2, **router_kw):
    router = FleetRouter(**router_kw)
    stacks = []
    for i in range(n):
        stack = ServingStack(Engine(EngineConfig(**BASE)))
        stacks.append(stack)
        router.add_local(stack, f"r{i}")
    return router, stacks


def _close(stacks):
    for s in stacks:
        s.close()


def _flight(kind):
    return obs.flight.get_recorder().snapshot(kind=kind)


# -- fault-spec determinism ---------------------------------------------------
class TestFaultSpec:
    def test_count_based_selectors(self):
        faults.configure("a@2;b@2..3;c@3+;d@every:2")
        assert [faults.fire("a") for _ in range(4)] == \
            [False, True, False, False]
        assert [faults.fire("b") for _ in range(4)] == \
            [False, True, True, False]
        assert [faults.fire("c") for _ in range(4)] == \
            [False, False, True, True]
        assert [faults.fire("d") for _ in range(4)] == \
            [False, True, False, True]
        assert not faults.fire("unwired")

    def test_prob_selector_is_seed_deterministic(self):
        faults.configure("x@p:0.5:42")
        first = [faults.fire("x") for _ in range(64)]
        faults.configure("x@p:0.5:42")
        assert [faults.fire("x") for _ in range(64)] == first
        faults.configure("x@p:0.5:43")
        assert [faults.fire("x") for _ in range(64)] != first
        assert any(first) and not all(first)

    def test_same_spec_same_flight_event_sequence(self):
        """The reproducibility acceptance criterion, at the harness level:
        the same spec against the same hit sequence fires identically and
        records the identical fault_injected event sequence."""
        def drive():
            faults.configure("p1@2;p2@every:3")
            for _ in range(9):
                faults.fire("p1")
                faults.fire("p2")
            return [
                (e["point"], e["hit"])
                for e in _flight("fault_injected")
            ]

        first = drive()
        obs.flight.get_recorder().reset()
        assert drive() == first
        assert first == [("p1", 2), ("p2", 3), ("p2", 6), ("p2", 9)]

    def test_malformed_clause_skipped_and_firing_recorded(self):
        faults.configure("not a clause;ok@1")
        assert faults.active()
        assert faults.fire("ok", extra="ctx")
        assert not faults.fire("ok")
        assert obs.FAULT_INJECTIONS.value(point="ok") == 1
        events = _flight("fault_injected")
        assert events and events[-1]["point"] == "ok"
        s = faults.summary()
        assert s["fired"] == {"ok": 1} and s["hits"] == {"ok": 2}

    def test_env_spec_loads_lazily(self, monkeypatch):
        faults.reset()
        monkeypatch.setenv(faults.ENV_FAULTS, "envpoint@1")
        assert faults.fire("envpoint")
        assert not faults.fire("envpoint")

    def test_maybe_raise_class_and_instance(self):
        faults.configure("e@1..2")
        with pytest.raises(TimeoutError, match="injected"):
            faults.maybe_raise("e", TimeoutError, "injected timeout")
        with pytest.raises(ConnectionError, match="boom"):
            faults.maybe_raise("e", ConnectionError("boom"))
        faults.maybe_raise("e", RuntimeError)  # hit 3: no fire, no raise


# -- circuit breaker ----------------------------------------------------------
class TestCircuitBreaker:
    def _reg(self, cooldown=0.2):
        reg = ReplicaRegistry(eject_cooldown=cooldown)
        reg.register(ReplicaInfo(replica_id="a", local=True))
        reg.register(ReplicaInfo(replica_id="b", local=True))
        return reg

    def test_failures_walk_healthy_suspect_ejected(self):
        reg = self._reg()
        reg.note_result("a", ok=False)
        assert reg.health_of("a").state == "suspect"
        assert {i.replica_id for i in reg.alive()} == {"a", "b"}
        for _ in range(EJECT_AFTER_FAILURES - 1):
            reg.note_result("a", ok=False)
        assert reg.health_of("a").state == "ejected"
        assert [i.replica_id for i in reg.alive()] == ["b"]
        assert obs.FLEET_EJECTIONS.value() == 1
        assert _flight("replica_ejected")[-1]["replica"] == "a"
        # Non-admitting reads still see the ejected replica.
        assert {i.replica_id for i in reg.alive(admitting=False)} == \
            {"a", "b"}

    def test_success_closes_the_breaker(self):
        reg = self._reg()
        reg.note_result("a", ok=False)
        reg.note_result("a", ok=False)
        reg.note_result("a", ok=True)
        h = reg.health_of("a")
        assert h.state == "healthy" and h.consecutive_failures == 0

    def test_half_open_probe_gates_readmission(self):
        reg = self._reg(cooldown=0.15)
        for _ in range(EJECT_AFTER_FAILURES):
            reg.note_result("a", ok=False)
        assert [i.replica_id for i in reg.alive()] == ["b"]
        time.sleep(0.2)
        # Cooldown elapsed: half-open, admitting again.
        assert {i.replica_id for i in reg.alive()} == {"a", "b"}
        reg.begin_probe("a")
        # One probe in flight: no second request admitted.
        assert [i.replica_id for i in reg.alive()] == ["b"]
        reg.note_result("a", ok=True)
        assert reg.health_of("a").state == "healthy"
        assert {i.replica_id for i in reg.alive()} == {"a", "b"}

    def test_failed_probe_reejects_with_backoff(self):
        reg = self._reg(cooldown=0.15)
        for _ in range(EJECT_AFTER_FAILURES):
            reg.note_result("a", ok=False)
        time.sleep(0.2)
        reg.begin_probe("a")
        reg.note_result("a", ok=False)  # the probe failed
        h = reg.health_of("a")
        assert h.state == "ejected" and h.ejections == 2
        # Doubled cooldown: ~0.3 s remaining, not ~0.15.
        assert h.ejected_until - time.monotonic() > 0.2

    def test_heartbeat_staleness_marks_remote_suspect(self):
        reg = ReplicaRegistry(ttl_s=0.5)
        reg.register(ReplicaInfo(replica_id="far", url="http://x"))
        assert [i.replica_id for i in reg.alive()] == ["far"]
        assert reg.health_of("far").state == "healthy"
        time.sleep(0.3)  # > ttl/2, < ttl
        assert [i.replica_id for i in reg.alive()] == ["far"]
        assert reg.health_of("far").state == "suspect"

    def test_reregistration_resets_health(self):
        reg = self._reg()
        for _ in range(EJECT_AFTER_FAILURES):
            reg.note_result("a", ok=False)
        reg.register(ReplicaInfo(replica_id="a", local=True))
        assert reg.health_of("a").state == "healthy"
        assert {i.replica_id for i in reg.alive()} == {"a", "b"}


# -- router failover ----------------------------------------------------------
class _Flaky:
    """Replica-handle proxy whose chat_completion fails while the shared
    budget lasts — whichever replica the router picks first eats it."""

    def __init__(self, inner, budget, exc=None):
        self._inner = inner
        self._budget = budget
        self._exc = exc or ConnectionError("injected connect failure")

    def chat_completion(self, body):
        if self._budget["n"] > 0:
            self._budget["n"] -= 1
            raise self._exc
        return self._inner.chat_completion(body)

    def __getattr__(self, name):
        return getattr(self._inner, name)


class TestRouterFailover:
    BODY = {
        "messages": [{"role": "user", "content": "contain this failure"}],
        "max_tokens": 8, "temperature": 0,
    }

    def test_connect_failure_retries_on_another_replica(self):
        router, stacks = _fleet(2)
        try:
            budget = {"n": 1}
            for rid in ("r0", "r1"):
                info = router.registry.get(rid)
                info.handle = _Flaky(info.handle, budget)
            resp = router.complete(dict(self.BODY))
            text = resp["choices"][0]["message"]["content"]
            assert text
            assert budget["n"] == 0
            assert obs.FLEET_RETRIES.value() == 1
            retries = _flight("fleet_retry")
            assert retries and retries[-1]["attempt"] == 1
            # The failed call fed the breaker.
            states = set(router.registry.health_snapshot().values())
            assert "suspect" in states
        finally:
            _close(stacks)

    def test_non_retryable_400_is_not_retried(self):
        router, stacks = _fleet(2)
        try:
            from opsagent_tpu.serving.scheduler import RequestError

            budget = {"n": 4}
            err = RequestError("prompt too long", 400)
            for rid in ("r0", "r1"):
                info = router.registry.get(rid)
                info.handle = _Flaky(info.handle, budget, exc=err)
            with pytest.raises(RequestError):
                router.complete(dict(self.BODY))
            assert budget["n"] == 3  # one attempt, no retries
            assert obs.FLEET_RETRIES.value() == 0
        finally:
            _close(stacks)

    def test_mid_stream_failover_resumes_byte_identical(self):
        """THE chaos acceptance gate: a replica dies mid-decode (injected
        mid-SSE disconnect); the stream completes on the survivor with no
        error chunk, no gap/duplicate at the seam, and greedy text
        byte-identical to the fault-free run."""
        body = {
            "messages": [{"role": "user", "content": "steady stream"}],
            "max_tokens": 12, "temperature": 0, "stream": True,
        }

        def collect(router):
            chunks = list(router.complete_stream(dict(body)))
            assert all("error" not in c for c in chunks), chunks
            heads = [
                c for c in chunks
                if "role" in c["choices"][0].get("delta", {})
            ]
            finals = [
                c for c in chunks if c["choices"][0].get("finish_reason")
            ]
            assert len(heads) == 1, "role chunk must be emitted exactly once"
            assert len(finals) == 1
            return "".join(
                c["choices"][0]["delta"].get("content") or ""
                for c in chunks
            )

        router, stacks = _fleet(2)
        try:
            reference = collect(router)
            assert reference

            # Same fleet, faults on: the 5th chunk pull dies mid-stream.
            faults.configure("fleet.stream_disconnect@5")
            resumed = collect(router)
            assert resumed == reference
            assert obs.FLEET_FAILOVERS.value() >= 1
            failovers = _flight("failover")
            assert failovers and failovers[-1]["emitted_chars"] > 0
            assert _flight("fault_injected")
            # Zero-post-warmup-compiles invariant holds throughout.
            compiles = [
                e for e in _flight("anomaly")
                if e.get("reason") == "post_warmup_compile"
            ]
            assert not compiles
        finally:
            _close(stacks)

    def test_stream_failover_is_deterministic_under_fixed_spec(self):
        """Same spec, same workload -> same flight-event sequence (the
        reproducibility acceptance criterion, end to end)."""
        body = {
            "messages": [{"role": "user", "content": "replay me"}],
            "max_tokens": 8, "temperature": 0, "stream": True,
        }

        def run_once():
            router, stacks = _fleet(2)
            try:
                faults.configure("fleet.stream_disconnect@4")
                list(router.complete_stream(dict(body)))
                return [
                    (e["point"], e["hit"])
                    for e in _flight("fault_injected")
                ]
            finally:
                _close(stacks)

        first = run_once()
        obs.flight.get_recorder().reset()
        obs.get_registry().reset()
        assert run_once() == first
        assert first == [("fleet.stream_disconnect", 4)]

    def test_hedged_completion_races_a_backup(self):
        router, stacks = _fleet(2, hedge_queue_depth=0)
        try:
            resp = router.complete(dict(self.BODY))
            assert resp["choices"][0]["message"]["content"]
            assert sum(
                obs.FLEET_HEDGES.value(**{"class": c})
                for c in obs.SLO_CLASSES
            ) >= 1
            hedges = _flight("fleet_hedge")
            assert hedges and {
                hedges[-1]["primary"], hedges[-1]["backup"]
            } == {"r0", "r1"}
        finally:
            _close(stacks)


# -- overload shedding --------------------------------------------------------
def _serve_router_on_port(router):
    """Run the router app on a real localhost port; (base_url, stop)."""
    app = build_router_app(router)
    loop = asyncio.new_event_loop()
    runner_box = {}

    async def _start():
        from aiohttp import web

        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        runner_box["runner"] = runner
        runner_box["port"] = runner.addresses[0][1]

    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()
    asyncio.run_coroutine_threadsafe(_start(), loop).result(timeout=30)

    def stop():
        async def _stop():
            await runner_box["runner"].cleanup()

        asyncio.run_coroutine_threadsafe(_stop(), loop).result(timeout=30)
        loop.call_soon_threadsafe(loop.stop)
        t.join(timeout=10)

    return f"http://127.0.0.1:{runner_box['port']}", stop


class TestOverload:
    def test_shed_raises_429_with_retry_after(self):
        router, stacks = _fleet(2, shed_queue_depth=0)
        try:
            with pytest.raises(OverloadError) as ei:
                router.complete({
                    "messages": [{"role": "user", "content": "too much"}],
                    "max_tokens": 4, "temperature": 0,
                })
            assert ei.value.status == 429
            assert ei.value.retry_after_s >= 1
            assert sum(
                obs.FLEET_SHED.value(**{"class": c})
                for c in obs.SLO_CLASSES
            ) == 1
            assert obs.FLEET_REQUESTS.value(outcome="shed") == 1
            assert _flight("request_shed")
        finally:
            _close(stacks)

    def test_forced_route_bypasses_the_shed(self):
        router, stacks = _fleet(2, shed_queue_depth=0)
        try:
            resp = router.complete({
                "messages": [{"role": "user", "content": "operator"}],
                "max_tokens": 4, "temperature": 0,
            }, force_replica="r0")
            assert resp["choices"][0]["message"]["content"]
        finally:
            _close(stacks)

    def test_http_429_retry_after_and_slo_stays_green(self, monkeypatch):
        """Traffic above the watermark gets 429 + Retry-After over HTTP
        while accepted requests' SLO verdict stays green — sheds never
        reach an engine, so the error-rate SLO cannot breach."""
        from opsagent_tpu.cli.slocheck import run_slo_check

        monkeypatch.setenv("OPSAGENT_SLO_TTFT_MS", "60000")
        router, stacks = _fleet(2)
        url, stop = _serve_router_on_port(router)
        try:
            accepted = urllib.request.urlopen(urllib.request.Request(
                url + "/v1/chat/completions",
                data=json.dumps({
                    "messages": [{"role": "user", "content": "admit me"}],
                    "max_tokens": 4, "temperature": 0,
                }).encode(),
                headers={"Content-Type": "application/json"},
            ), timeout=120)
            assert accepted.status == 200

            router.shed_queue_depth = 0  # watermark now below all traffic
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(urllib.request.Request(
                    url + "/v1/chat/completions",
                    data=json.dumps({
                        "messages": [{"role": "user", "content": "surge"}],
                        "max_tokens": 4, "temperature": 0,
                    }).encode(),
                    headers={"Content-Type": "application/json"},
                ), timeout=60)
            assert ei.value.code == 429
            assert int(ei.value.headers["Retry-After"]) >= 1

            # The fleet SLO gate holds: sheds are not engine errors.
            assert run_slo_check(url=url) == 0
            health = json.loads(
                urllib.request.urlopen(url + "/healthz", timeout=30).read()
            )
            assert health["shed_queue_depth"] == 0
            assert set(health["health"]) == {"r0", "r1"}
        finally:
            stop()
            _close(stacks)

    def test_slo_check_passes_while_faults_fire(self, monkeypatch):
        """The fleet-chaos CI gate: seeded faults firing through the
        router, zero failed requests, >= 1 failover, and `opsagent
        slo-check` against the router still exits 0."""
        from opsagent_tpu.cli.slocheck import run_slo_check

        monkeypatch.setenv("OPSAGENT_SLO_TTFT_MS", "60000")
        router, stacks = _fleet(2)
        url, stop = _serve_router_on_port(router)
        try:
            faults.configure("fleet.stream_disconnect@3")
            failed = []
            for i in range(3):
                gen = router.complete_stream({
                    "messages": [
                        {"role": "user", "content": f"chaos smoke {i}"}
                    ],
                    "max_tokens": 6, "temperature": 0, "stream": True,
                })
                chunks = list(gen)
                if any("error" in c for c in chunks):
                    failed.append(i)
            assert not failed
            assert obs.FLEET_FAILOVERS.value() >= 1
            assert run_slo_check(url=url) == 0
        finally:
            stop()
            _close(stacks)


# -- scheduler fault points ---------------------------------------------------
SCHED_CFG = dict(
    model="tiny-test", dtype=jnp.float32, tp=1, page_size=8,
    num_pages=256, max_pages_per_seq=32, max_batch_size=4,
    prefill_buckets=(16,),
)


def _wait_running(sched, timeout=30):
    deadline = time.time() + timeout
    while time.time() < deadline and not sched._running:
        time.sleep(0.01)
    assert sched._running, "request never started decoding"


class TestSchedulerFaults:
    def test_out_of_pages_storm_retries_to_completion(self):
        eng = Engine(EngineConfig(**SCHED_CFG))
        sched = Scheduler(eng)
        sched.start()
        try:
            faults.configure("sched.out_of_pages@1..3")
            req = sched.submit(
                Request([1, 2, 3, 4], SamplingParams(max_tokens=4))
            )
            assert req.done.wait(60), "storm never cleared"
            assert not req.error, req.error
            assert len(req.tokens) >= 1
            assert obs.FAULT_INJECTIONS.value(
                point="sched.out_of_pages"
            ) == 3
        finally:
            sched.stop()

    def test_step_faults_force_engine_restart_and_recovery(self):
        eng = Engine(EngineConfig(**SCHED_CFG))
        sched = Scheduler(
            eng, engine_factory=lambda: Engine(EngineConfig(**SCHED_CFG)),
        )
        sched.start()
        try:
            req = sched.submit(
                Request([5, 6, 7], SamplingParams(max_tokens=6))
            )
            _wait_running(sched)
            # Three consecutive injected tick faults = the loop's
            # persistent-failure threshold -> forced engine restart.
            faults.configure("sched.step_fault@1..3")
            assert req.done.wait(120), "never recovered from step faults"
            assert not req.error, req.error
            assert sched._restarts == 1
            assert req.finish_reason in ("stop", "length")
            assert obs.FAULT_INJECTIONS.value(
                point="sched.step_fault"
            ) == 3
        finally:
            sched.stop()

    def test_requeue_salvaged_resets_admission_clock(self):
        """Satellite: a salvaged re-admission must not double-count its
        queue wait — scheduler.py resets enqueued_s in _requeue_salvaged,
        so a request that already spent (mock) ages in flight is NOT
        admission-timed-out on re-admission, and the re-admission's
        queued goodput phase restarts from the re-queue instant."""
        eng = Engine(EngineConfig(**SCHED_CFG))
        sched = Scheduler(
            eng,
            engine_factory=lambda: Engine(EngineConfig(**SCHED_CFG)),
            admission_timeout_s=5.0,
        )
        sched.start()
        try:
            req = sched.submit(
                Request([9, 8, 7], SamplingParams(max_tokens=6))
            )
            _wait_running(sched)
            # Simulate a request that has been alive far past the
            # admission timeout, then kill the engine under it.
            req.enqueued_s = time.perf_counter() - 600.0
            queued_before = obs.attribution.GOODPUT_SECONDS.value(
                phase="queued"
            )

            def boom(*a, **k):
                raise RuntimeError("device runtime lost")

            sched.engine.step_block = boom
            assert req.done.wait(120), "salvaged request never completed"
            assert not req.error, req.error  # NOT "admission timed out"
            assert sched._restarts == 1
            # The clock was reset: the re-admission's recorded queue wait
            # is the seconds since the re-queue, not the fake 600.
            queued_delta = obs.attribution.GOODPUT_SECONDS.value(
                phase="queued"
            ) - queued_before
            assert queued_delta < 60.0, (
                f"queue wait double-counted: {queued_delta:.1f}s recorded"
            )
        finally:
            sched.stop()

    def test_admission_timeout_reclaims_with_async_pipeline_in_flight(self):
        """Satellite: admission_timeout_s under async_depth=2 — the
        timed-out request reports the timeout while the pipeline is mid-
        flight, and after the batch drains the page pool is exactly
        conserved (nothing leaked by the timed-out admission)."""
        # prefix_cache off: finished sequences must return EVERY page to
        # the allocator, so conservation is an exact equality (the trie
        # would otherwise deliberately retain full prompt pages).
        cfg = dict(
            SCHED_CFG, max_batch_size=1, max_pages_per_seq=40,
            num_pages=64, async_depth=2, prefix_cache=False,
        )
        eng = Engine(EngineConfig(**cfg))
        sched = Scheduler(eng, admission_timeout_s=5.0)
        free0 = eng.alloc.free_pages
        sched.start()
        try:
            # A long-running request occupies the single batch slot with
            # the async lookahead pipeline active.
            req_a = sched.submit(
                Request([1, 2, 3, 4], SamplingParams(max_tokens=64))
            )
            _wait_running(sched)
            # B arrives already past its admission deadline (backdated).
            # While A saturates the batch B just waits; the moment A's
            # slot frees, the admission pass times B out instead of
            # admitting it.
            req_b = Request([5, 6, 7, 8], SamplingParams(max_tokens=4))
            req_b.enqueued_s = time.perf_counter() - 600.0
            sched.submit(req_b)
            assert req_b.done.wait(120), "timed-out request never reported"
            assert "admission timed out" in req_b.error
            assert req_b.seq_id is None  # never admitted, holds no pages
            assert req_a.done.wait(120), "pipelined request never finished"
            assert not req_a.error, req_a.error
            # Page conservation with the pipeline drained.
            deadline = time.time() + 30
            while time.time() < deadline and \
                    eng.alloc.free_pages != free0:
                time.sleep(0.05)
            assert eng.alloc.free_pages == free0
            assert obs.ENGINE_REQUESTS.value(outcome="timeout") == 1
        finally:
            sched.stop()


# -- agent tool fault points --------------------------------------------------
def _tp(thought="", name="", input="", observation="", final=""):
    return json.dumps({
        "question": "q",
        "thought": thought,
        "action": {"name": name, "input": input},
        "observation": observation,
        "final_answer": final,
    })


def _msgs():
    return [
        {"role": "system", "content": "you are a test agent"},
        {"role": "user", "content": "count the pods"},
    ]


class TestToolFaults:
    def test_injected_tool_failure_becomes_observation(
        self, scripted_llm, fake_tools
    ):
        from opsagent_tpu.agent.react import assistant_with_config

        calls = []

        def fake_kubectl(cmd):
            calls.append(cmd)
            return "3 pods"

        fake_tools({"kubectl": fake_kubectl})
        fake = scripted_llm([
            _tp(name="kubectl", input="get pods"),
            _tp(name="kubectl", input="get pods"),
            _tp(observation="3 pods", final="There are 3 pods."),
        ])
        faults.configure("tool.exec@1")
        out, _history = assistant_with_config("fake://m", _msgs())
        assert "There are 3 pods." in out
        # First invocation was injected to fail BEFORE the subprocess
        # ran; the loop fed the failure back as an observation and the
        # model's retry executed for real.
        assert calls == ["get pods"]
        assert obs.FAULT_INJECTIONS.value(point="tool.exec") == 1
        assert obs.TOOL_CALLS.value(tool="kubectl", outcome="error") == 1
        assert obs.TOOL_CALLS.value(tool="kubectl", outcome="ok") == 1
        fed_back = fake.requests[1]["messages"][-1]["content"]
        assert "injected tool subprocess failure" in fed_back

    def test_injected_tool_timeout_becomes_observation(
        self, scripted_llm, fake_tools
    ):
        fake_tools({"kubectl": lambda cmd: "ok"})
        from opsagent_tpu.agent.react import assistant_with_config

        scripted_llm([
            _tp(name="kubectl", input="get ns"),
            _tp(observation="noted", final="Cluster query timed out."),
        ])
        faults.configure("tool.timeout@1")
        out, _ = assistant_with_config("fake://m", _msgs())
        assert "timed out" in out.lower()
        assert obs.FAULT_INJECTIONS.value(point="tool.timeout") == 1
        assert obs.TOOL_CALLS.value(tool="kubectl", outcome="error") == 1


# -- KV transfer fault points -------------------------------------------------
class TestTransferFaults:
    def _records(self):
        import numpy as np

        from opsagent_tpu.serving.fleet.transfer import pack_entries
        from opsagent_tpu.serving.offload.pool import HostPagePool

        pool = HostPagePool(page_size=4, capacity_bytes=1 << 20)
        template = {"k": np.arange(4, dtype=np.float32).reshape(2, 2)}
        pool.put([1, 2, 3, 4], template)
        return pack_entries(pool.entries_for([1, 2, 3, 4])), template

    def test_injected_corruption_rejected_by_digest(self):
        from opsagent_tpu.serving.fleet.transfer import unpack_entries

        records, template = self._records()
        faults.configure("transfer.corrupt@1")
        assert unpack_entries(records, template) == []
        assert obs.FLEET_KV_IMPORT_REJECTS.value() == 1
        rejects = [
            e for e in _flight("anomaly")
            if e.get("reason") == "kv_import_reject"
        ]
        assert rejects and rejects[-1]["cause"] == "digest_mismatch"

    def test_injected_truncation_rejected_by_structure(self):
        from opsagent_tpu.serving.fleet.transfer import unpack_entries

        records, template = self._records()
        faults.configure("transfer.truncate@1")
        assert unpack_entries(records, template) == []
        assert obs.FLEET_KV_IMPORT_REJECTS.value() == 1


# -- heartbeat fault point + backoff ------------------------------------------
class TestHeartbeatContainment:
    def _membership(self):
        import queue as _q

        from opsagent_tpu.serving.fleet.client import FleetMembership

        class _Sched:
            _running: dict = {}
            _waiting: list = []
            _prefilling: dict = {}
            _queue = _q.Queue()

        class _Alloc:
            free_pages = 7

        class _Cfg:
            max_batch_size = 4
            page_size = 8
            tp = 1
            sp = 1
            ep = 1

        class _Eng:
            alloc = _Alloc()
            cfg = _Cfg()

            def prefix_digests(self):
                return []

        class _Stack:
            engine = _Eng()
            scheduler = _Sched()
            model_name = "tiny-test"

        return FleetMembership(
            _Stack(), "http://127.0.0.1:9", "http://127.0.0.1:8",
            replica_id="hb-test", heartbeat_interval_s=0.01,
        )

    def test_registration_failure_backs_off_with_jitter(self):
        from opsagent_tpu.serving.fleet.client import (
            REGISTER_BACKOFF_BASE_S,
            REGISTER_BACKOFF_CAP_S,
        )

        m = self._membership()
        posts = []

        def failing_post(path, body):
            posts.append(path)
            raise urllib.error.URLError("router down")

        m._post = failing_post
        assert not m.register()
        first_backoff = m._register_backoff_s
        assert first_backoff == 2 * REGISTER_BACKOFF_BASE_S
        assert m._next_register_s > time.monotonic()
        assert not m.register()
        # Backoff doubles per failure, capped.
        assert m._register_backoff_s == min(
            REGISTER_BACKOFF_CAP_S, 2 * first_backoff
        )
        assert m._next_register_s > time.monotonic()
        assert posts == ["/fleet/register", "/fleet/register"]

    def test_registration_success_resets_backoff(self):
        m = self._membership()
        m._post = lambda path, body: (_ for _ in ()).throw(
            urllib.error.URLError("down")
        )
        m.register()
        m._post = lambda path, body: {"status": "registered"}
        assert m.register()
        assert m._register_backoff_s == 0.0
        assert m._next_register_s == 0.0

    def test_heartbeat_survives_urlerror_and_drops_are_injected(self):
        m = self._membership()
        posts = []

        def post(path, body):
            posts.append(path)
            if path == "/fleet/heartbeat" and \
                    posts.count("/fleet/heartbeat") == 2:
                raise urllib.error.URLError("blip")
            return {"status": "ok"}

        m._post = post
        faults.configure("client.heartbeat_drop@2")
        m.start()  # registers, then beats every 10 ms
        try:
            deadline = time.time() + 10
            while time.time() < deadline and \
                    posts.count("/fleet/heartbeat") < 4:
                time.sleep(0.02)
        finally:
            m.stop(deregister=False)
        # Loop beat 2 was dropped before the wire (injected); a later
        # wire URLError did not kill the thread or deregister either.
        assert posts[0] == "/fleet/register"
        assert posts.count("/fleet/heartbeat") >= 4
        assert m.registered
        assert m.last_heartbeat_ok is not None
        assert obs.FAULT_INJECTIONS.value(point="client.heartbeat_drop") == 1
