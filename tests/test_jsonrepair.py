"""Tests for the JSON repair ladder (reference left pkg/utils/json.go untested)."""

import pytest

from opsagent_tpu.utils.jsonrepair import clean_json, extract_field, parse_json


def test_parse_strict():
    assert parse_json('{"a": 1}') == {"a": 1}


def test_parse_with_code_fence():
    s = 'Here you go:\n```json\n{"thought": "x", "final_answer": "done"}\n```\nEnjoy.'
    assert parse_json(s)["final_answer"] == "done"


def test_parse_with_surrounding_prose():
    s = 'Sure! {"a": "b"} hope that helps'
    assert parse_json(s) == {"a": "b"}


def test_raw_newlines_inside_strings():
    s = '{"final_answer": "line one\nline two"}'
    assert parse_json(s)["final_answer"] == "line one\nline two"


def test_trailing_commas():
    assert parse_json('{"a": [1, 2,], "b": 2,}') == {"a": [1, 2], "b": 2}


def test_unterminated_object_closed():
    s = '{"question": "q", "thought": "started but never finis'
    obj = parse_json(s)
    assert obj["question"] == "q"


def test_nested_braces_in_strings():
    s = 'prefix {"cmd": "kubectl get pods -o jsonpath={.items[0]}", "n": 1} suffix'
    assert parse_json(s)["n"] == 1


def test_unparseable_raises():
    with pytest.raises(ValueError):
        parse_json("no json here at all")


def test_extract_field_strict():
    assert extract_field('{"final_answer": "yes"}', "final_answer") == "yes"


def test_extract_field_regex_fallback():
    s = 'garbage "final_answer": "it has \\"quotes\\" inside" garbage'
    assert extract_field(s, "final_answer") == 'it has "quotes" inside'


def test_extract_field_missing():
    assert extract_field('{"a": 1}', "missing") == ""


def test_extract_field_object_value():
    s = '{"action": {"name": "kubectl", "input": "get ns"}}'
    out = extract_field(s, "action")
    assert "kubectl" in out


def test_clean_json_idempotent_on_valid():
    s = '{"a": "b"}'
    assert clean_json(s) == s
