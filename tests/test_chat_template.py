"""Chat-template goldens: the exact rendered strings each model family was
trained on, pinned so a template-token drift (missing header, changed
marker, reordered tool preamble) fails loudly (VERDICT round-1 item 6:
'fails if any ... template token drifts')."""

import json

from opsagent_tpu.serving.chat_template import (
    apply_chat_template,
    byte_template_ids,
    render_llama3,
    render_qwen,
)
from opsagent_tpu.serving.tokenizer import ByteTokenizer

CHAT = [
    {"role": "system", "content": "You are a k8s ops assistant."},
    {"role": "user", "content": "count namespaces"},
]


def test_llama3_template_golden():
    assert render_llama3(CHAT) == (
        "<|begin_of_text|>"
        "<|start_header_id|>system<|end_header_id|>\n\n"
        "You are a k8s ops assistant.<|eot_id|>"
        "<|start_header_id|>user<|end_header_id|>\n\n"
        "count namespaces<|eot_id|>"
        "<|start_header_id|>assistant<|end_header_id|>\n\n"
    )


def test_qwen_template_golden():
    assert render_qwen(CHAT) == (
        "<|im_start|>system\nYou are a k8s ops assistant.<|im_end|>\n"
        "<|im_start|>user\ncount namespaces<|im_end|>\n"
        "<|im_start|>assistant\n"
    )


def test_tools_merge_into_system():
    tools = [{
        "type": "function",
        "function": {
            "name": "kubectl",
            "description": "run kubectl",
            "parameters": {"type": "object"},
        },
    }]
    text = render_qwen(CHAT, tools)
    # One system block only, with the tool schema appended to it.
    assert text.count("<|im_start|>system") == 1
    assert "kubectl: run kubectl" in text
    assert '{"type": "object"}' in text
    # Without a system message, one is synthesized at the front.
    text2 = render_llama3([{"role": "user", "content": "hi"}], tools)
    assert text2.index("system") < text2.index("user")


def test_byte_template_roundtrip_markers():
    tok = ByteTokenizer()
    ids = byte_template_ids(tok, CHAT)
    assert ids[0] == tok.bos_id
    assert ids[1] == tok.SYS
    assert ids.count(tok.END) == 2
    assert ids[-1] == tok.ASSISTANT
    # Content bytes survive exactly.
    assert tok.decode(ids[2:ids.index(tok.END)]) == CHAT[0]["content"]


def test_apply_chat_template_family_dispatch():
    tok = ByteTokenizer()
    assert apply_chat_template(tok, CHAT) == byte_template_ids(tok, CHAT)

    class StrTok:
        hf = None

        def encode(self, s):
            return s  # identity: lets us inspect the rendered string

    assert "<|im_start|>" in apply_chat_template(
        StrTok(), CHAT, model_family="qwen2.5-7b-instruct"
    )
    assert "<|im_start|>" in apply_chat_template(
        StrTok(), CHAT, model_family="deepseek-moe-16b"
    )
    assert "<|begin_of_text|>" in apply_chat_template(
        StrTok(), CHAT, model_family="llama-3-8b-instruct"
    )


def test_tool_call_assistant_message_renders_as_json():
    msgs = CHAT + [{
        "role": "assistant",
        "tool_calls": [{
            "id": "call_0", "type": "function",
            "function": {"name": "kubectl", "arguments": "{}"},
        }],
    }]
    text = render_llama3(msgs)
    block = text.split("<|start_header_id|>assistant<|end_header_id|>")[1]
    parsed = json.loads(block.split("<|eot_id|>")[0].strip())
    assert parsed["tool_calls"][0]["function"]["name"] == "kubectl"
