"""Long-context rope scaling (ops/rope.py): llama3 banded interpolation
and YaRN, against independently computed reference values."""

import math

import jax.numpy as jnp
import numpy as np

from opsagent_tpu.models.config import RopeScalingConfig, get_config_preset
from opsagent_tpu.ops.rope import rope_table, yarn_get_mscale


def _freqs_from_table(dim, theta, scaling):
    """Recover inv freqs by evaluating the table at position 1."""
    cos, sin = rope_table(jnp.asarray([[1]]), dim, theta, scaling=scaling)
    # angle = inv_freq * 1; magnitude factor divides out via atan2.
    return np.arctan2(np.asarray(sin)[0, 0], np.asarray(cos)[0, 0])


def test_llama3_banded_interpolation():
    dim, theta = 128, 500000.0
    sc = RopeScalingConfig(
        rope_type="llama3", factor=8.0, original_max_position=8192,
        low_freq_factor=1.0, high_freq_factor=4.0,
    )
    base = _freqs_from_table(dim, theta, None)
    scaled = _freqs_from_table(dim, theta, sc)
    # Reference formula, straight from the HF implementation.
    ref = []
    for inv in base:
        wl = 2 * math.pi / inv
        low_wl = 8192 / 1.0
        high_wl = 8192 / 4.0
        if wl > low_wl:
            ref.append(inv / 8.0)
        elif wl < high_wl:
            ref.append(inv)
        else:
            smooth = (8192 / wl - 1.0) / (4.0 - 1.0)
            ref.append((1 - smooth) * inv / 8.0 + smooth * inv)
    np.testing.assert_allclose(scaled, ref, rtol=1e-5)
    # High-frequency dims untouched; lowest-frequency dims divided by 8.
    assert np.isclose(scaled[0], base[0], rtol=1e-6)
    assert np.isclose(scaled[-1], base[-1] / 8.0, rtol=1e-4)


def test_yarn_interpolation_and_mscale():
    dim, theta = 64, 10000.0
    sc = RopeScalingConfig(
        rope_type="yarn", factor=40.0, original_max_position=4096,
        beta_fast=32.0, beta_slow=1.0, mscale=0.707, mscale_all_dim=0.707,
    )
    base_cos, _ = rope_table(jnp.asarray([[0]]), dim, theta)
    sc_cos, _ = rope_table(jnp.asarray([[0]]), dim, theta, scaling=sc)
    # mscale/mscale_all_dim equal -> table magnitude factor is 1.
    np.testing.assert_allclose(
        np.asarray(sc_cos), np.asarray(base_cos), rtol=1e-6
    )

    base = _freqs_from_table(dim, theta, None)
    scaled = _freqs_from_table(dim, theta, sc)
    # Fastest dims extrapolate (unchanged); slowest fully interpolate.
    assert np.isclose(scaled[0], base[0], rtol=1e-5)
    assert np.isclose(scaled[-1], base[-1] / 40.0, rtol=1e-3)
    # Monotone nonincreasing frequencies, no NaN.
    assert np.all(np.diff(scaled) <= 1e-9)

    # V3-style mscale_all_dim=1.0 vs mscale=1.0 -> magnitude factor 1,
    # but with mscale_all_dim=0 the factor is yarn_get_mscale(40, 1.0).
    sc2 = RopeScalingConfig(
        rope_type="yarn", factor=40.0, original_max_position=4096,
        mscale=1.0, mscale_all_dim=0.0,
    )
    c2, _ = rope_table(jnp.asarray([[0]]), dim, theta, scaling=sc2)
    np.testing.assert_allclose(
        np.asarray(c2), np.asarray(base_cos) * yarn_get_mscale(40.0, 1.0),
        rtol=1e-6,
    )


def test_deepseek_presets_reopen_scaled_window():
    for name in ("deepseek-v2-lite", "deepseek-v3"):
        cfg = get_config_preset(name)
        assert cfg.rope_scaling is not None
        assert cfg.rope_scaling.rope_type == "yarn"
        assert cfg.max_position == 163840


def test_llama31_preset_scaled():
    cfg = get_config_preset("llama-3.1-70b-instruct")
    assert cfg.rope_scaling.rope_type == "llama3"


def test_scaled_model_forward_finite_past_native_window():
    """A tiny yarn-scaled model decodes at positions past the original
    window without NaN (the point of the scaling)."""
    import dataclasses

    import jax

    from opsagent_tpu.models import llama

    cfg = dataclasses.replace(
        get_config_preset("tiny-mla"),
        max_position=8192,
        rope_scaling=RopeScalingConfig(
            rope_type="yarn", factor=16.0, original_max_position=512,
            mscale=1.0, mscale_all_dim=1.0,
        ),
    )
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (1, 700), 0, cfg.vocab_size
    )
    logits = llama.forward_full(params, cfg, tokens, dtype=jnp.float32)
    assert bool(jnp.isfinite(logits).all())
