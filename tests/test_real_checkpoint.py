"""Real-checkpoint end-to-end coverage.

Two layers:

- ``test_agent_loop_from_saved_checkpoint``: hermetic. Saves a tiny model
  as an HF-format safetensors checkpoint, boots a serving engine FROM THE
  FILE (models.loader path), and runs the full ReAct agent loop against it
  over the tpu:// in-process provider with a kubectl replay script —
  the exact flow scripts/run_real_checkpoint.py drives with real weights.
  The ToolPrompt constraint (agent/react.py tpu:// branch) guarantees
  schema-valid JSON even from random weights, so the loop's mechanics are
  fully exercised without a trained model.

- ``test_real_open_weights_checkpoint``: runs only when
  OPSAGENT_CHECKPOINT points at a real HF checkpoint dir (e.g.
  Llama-3-8B-Instruct); drives scripts/run_real_checkpoint.py end to end.
  This is the BASELINE config-2 capability proof (the reference instead
  calls GPT-4 remotely: reference pkg/handlers/execute.go:205).
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def replay_kubectl(tmp_path, monkeypatch):
    from opsagent_tpu.tools.replay import NAMESPACES_SCRIPT, install_replay_kubectl

    # Record the current PATH with monkeypatch so teardown restores it even
    # though install_replay_kubectl mutates os.environ directly.
    monkeypatch.setenv("PATH", os.environ["PATH"])
    install_replay_kubectl(NAMESPACES_SCRIPT, str(tmp_path / "bin"))


def test_agent_loop_from_saved_checkpoint(tmp_path, replay_kubectl):
    from opsagent_tpu.agent.prompts import REACT_SYSTEM_PROMPT
    from opsagent_tpu.agent.react import assistant_with_config
    from opsagent_tpu.models import llama
    from opsagent_tpu.models.config import TINY_TEST
    from opsagent_tpu.models.loader import save_checkpoint
    from opsagent_tpu.serving import api as serving_api
    from opsagent_tpu.serving.engine import Engine, EngineConfig

    ckpt = tmp_path / "model.safetensors"
    params = llama.init_params(
        TINY_TEST, jax.random.PRNGKey(7), dtype=jnp.float32
    )
    save_checkpoint(str(ckpt), params)

    engine = Engine(EngineConfig(
        model="tiny-test",
        checkpoint=str(ckpt),
        dtype=jnp.float32,
        num_pages=1024,
        page_size=16,
        max_pages_per_seq=320,
        max_batch_size=2,
        prefill_buckets=(256, 1024, 2048),
    ))
    stack = serving_api.ServingStack(engine)
    serving_api.install_stack("ckpt-e2e", stack)
    try:
        messages = [
            {"role": "system", "content": REACT_SYSTEM_PROMPT},
            {"role": "user",
             "content": "Here are the instructions: count namespaces"},
        ]
        answer, history = assistant_with_config(
            "tpu://ckpt-e2e", messages, 256, False, False, 2, "", ""
        )
        # The loop must terminate with SOME answer. Every assistant turn
        # must follow the ToolPrompt grammar from token one (the tpu://
        # constraint guarantees structure even for random weights — the
        # capability that deletes the reference's CleanJSON repair
        # ladder); a turn may still be truncated JSON when random weights
        # wander inside a string until the token cap, so completeness is
        # only asserted for turns that parse.
        assert isinstance(answer, str) and answer.strip()
        from opsagent_tpu.agent.prompts import SUMMARIZE_PROMPT

        constrained_turns = [
            m for i, m in enumerate(history)
            if m.get("role") == "assistant"
            # The loop's summarize-fallback call (after an unparseable
            # reply) is deliberately UNconstrained (react.py:206-208), so
            # only turns not answering SUMMARIZE_PROMPT carry the FSM
            # guarantee.
            and not (i > 0 and history[i - 1].get("content") == SUMMARIZE_PROMPT)
        ]
        assert constrained_turns
        for turn in constrained_turns:
            content = str(turn["content"])
            assert content.lstrip().startswith("{"), content[:80]
            try:
                parsed = json.loads(content)
            except json.JSONDecodeError:
                continue  # truncated at the generation cap
            assert isinstance(parsed, dict)
            assert set(parsed) <= {
                "question", "thought", "action", "observation",
                "final_answer",
            }
    finally:
        stack.close()
        serving_api.uninstall_stack("ckpt-e2e")


@pytest.mark.skipif(
    not os.environ.get("OPSAGENT_CHECKPOINT"),
    reason="OPSAGENT_CHECKPOINT not set (no real open-weights checkpoint "
           "available in this environment)",
)
def test_real_open_weights_checkpoint(tmp_path):
    out = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "scripts", "run_real_checkpoint.py"),
            "--transcript", str(tmp_path / "transcript.md"),
        ],
        capture_output=True, text=True, timeout=1800,
        env={**os.environ},
        cwd=REPO,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    last = out.stdout.strip().splitlines()[-1]
    assert json.loads(last)["ok"] is True
    assert (tmp_path / "transcript.md").exists()
