"""Engine tests: greedy generation vs the full-forward oracle, batching
equivalence, page lifecycle, constrained masks, streaming."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from opsagent_tpu.models import llama
from opsagent_tpu.models.config import TINY_TEST
from opsagent_tpu.serving.engine import Engine, EngineConfig
from opsagent_tpu.serving.kvcache import OutOfPages
from opsagent_tpu.serving.sampler import SamplingParams


@pytest.fixture(scope="module")
def engine():
    cfg = EngineConfig(
        model="tiny-test",
        dtype=jnp.float32,
        tp=1,
        page_size=4,
        num_pages=64,
        max_pages_per_seq=16,
        max_batch_size=4,
        prefill_buckets=(16, 32),
        seed=0,
    )
    return Engine(cfg)


def ref_greedy(engine, prompt, n):
    """Teacher-forced oracle: full causal forward + argmax each step."""
    toks = list(prompt)
    out = []
    for _ in range(n):
        logits = llama.forward_full(
            engine.params, engine.model_cfg, jnp.asarray([toks]), dtype=jnp.float32
        )
        nxt = int(jnp.argmax(logits[0, -1]))
        out.append(nxt)
        toks.append(nxt)
        if nxt == engine.tokenizer.eos_id:
            break
    return out


def test_generate_matches_oracle(engine):
    prompt = [257, 72, 101, 108, 108, 111]
    want = ref_greedy(engine, prompt, 8)
    got = engine.generate([prompt], SamplingParams(max_tokens=8))[0]
    assert got[: len(want)] == want


def test_batch_matches_individual(engine):
    p1 = [257, 10, 20, 30]
    p2 = [257, 99, 98, 97, 96, 95, 94]
    want1 = engine.generate([p1], SamplingParams(max_tokens=6))[0]
    want2 = engine.generate([p2], SamplingParams(max_tokens=6))[0]
    got = engine.generate([p1, p2], SamplingParams(max_tokens=6))
    assert got[0] == want1
    assert got[1] == want2


def test_long_generation_crosses_pages(engine):
    # page_size=4: 20 tokens forces several page extensions mid-decode.
    prompt = [257, 1, 2, 3, 4, 5, 6, 7, 8, 9]  # 10 tokens = 3 pages
    want = ref_greedy(engine, prompt, 14)
    got = engine.generate([prompt], SamplingParams(max_tokens=14))[0]
    assert got[: len(want)] == want


def test_pages_freed_after_finish(engine):
    free_before = engine.alloc.free_pages
    engine.generate([[257, 1, 2, 3, 4, 5]], SamplingParams(max_tokens=5))
    assert engine.alloc.free_pages == free_before
    assert engine.sequences == {}


def test_out_of_pages():
    cfg = EngineConfig(
        model="tiny-test", dtype=jnp.float32, tp=1,
        page_size=4, num_pages=2, max_pages_per_seq=2,
        max_batch_size=2, prefill_buckets=(16,),
    )
    small = Engine(cfg)
    sid = small.add_request([257, 1, 2, 3, 4, 5], SamplingParams(max_tokens=2))
    with pytest.raises(OutOfPages):
        small.add_request([257, 1, 2, 3, 4, 5], SamplingParams(max_tokens=2))
    small.finish(sid)
    # After freeing, admission succeeds again.
    sid2 = small.add_request([257, 9, 8, 7], SamplingParams(max_tokens=2))
    small.finish(sid2)


def test_constrained_mask_forbids_tokens(engine):
    prompt = [257, 42, 43, 44]
    free = ref_greedy(engine, prompt, 1)[0]

    def mask_fn(generated):
        m = np.ones((engine.model_cfg.vocab_size,), bool)
        m[free] = False  # forbid exactly the greedy choice
        return m

    sid = engine.add_request(prompt, SamplingParams(max_tokens=1), mask_fn=mask_fn)
    got = engine.finish(sid)
    assert got[0] != free


def test_stream_callback(engine):
    seen = []
    sid = engine.add_request(
        [257, 5, 6, 7], SamplingParams(max_tokens=4), stream=seen.append
    )
    while not engine.sequences[sid].done:
        engine.step([sid])
    toks = engine.finish(sid)
    assert seen == toks


def test_ttft_recorded(engine):
    sid = engine.add_request([257, 1], SamplingParams(max_tokens=1))
    seq_ttft = engine.sequences[sid].ttft_s
    engine.finish(sid)
    assert seq_ttft > 0
