"""Engine tests: greedy generation vs the full-forward oracle, batching
equivalence, page lifecycle, constrained masks, streaming."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from opsagent_tpu.models import llama
from opsagent_tpu.models.config import TINY_TEST
from opsagent_tpu.serving.engine import Engine, EngineConfig
from opsagent_tpu.serving.kvcache import OutOfPages
from opsagent_tpu.serving.sampler import SamplingParams


@pytest.fixture(scope="module")
def engine():
    cfg = EngineConfig(
        model="tiny-test",
        dtype=jnp.float32,
        tp=1,
        page_size=4,
        num_pages=64,
        max_pages_per_seq=16,
        max_batch_size=4,
        prefill_buckets=(16, 32),
        seed=0,
    )
    return Engine(cfg)


def ref_greedy(engine, prompt, n):
    """Teacher-forced oracle: full causal forward + argmax each step."""
    toks = list(prompt)
    out = []
    for _ in range(n):
        logits = llama.forward_full(
            engine.params, engine.model_cfg, jnp.asarray([toks]), dtype=jnp.float32
        )
        nxt = int(jnp.argmax(logits[0, -1]))
        out.append(nxt)
        toks.append(nxt)
        if nxt == engine.tokenizer.eos_id:
            break
    return out


def test_generate_matches_oracle(engine):
    prompt = [257, 72, 101, 108, 108, 111]
    want = ref_greedy(engine, prompt, 8)
    got = engine.generate([prompt], SamplingParams(max_tokens=8))[0]
    assert got[: len(want)] == want


def test_batch_matches_individual(engine):
    p1 = [257, 10, 20, 30]
    p2 = [257, 99, 98, 97, 96, 95, 94]
    want1 = engine.generate([p1], SamplingParams(max_tokens=6))[0]
    want2 = engine.generate([p2], SamplingParams(max_tokens=6))[0]
    got = engine.generate([p1, p2], SamplingParams(max_tokens=6))
    assert got[0] == want1
    assert got[1] == want2


def test_long_generation_crosses_pages(engine):
    # page_size=4: 20 tokens forces several page extensions mid-decode.
    prompt = [257, 1, 2, 3, 4, 5, 6, 7, 8, 9]  # 10 tokens = 3 pages
    want = ref_greedy(engine, prompt, 14)
    got = engine.generate([prompt], SamplingParams(max_tokens=14))[0]
    assert got[: len(want)] == want


def test_pages_freed_after_finish(engine):
    # First pass may DONATE full pages to the prefix trie (finish()
    # retains them as evictable cache, not leaked) — so the conservation
    # check runs on the steady state: an identical second generate must
    # return the allocator to exactly the first pass's level, and the
    # donated prefix must be re-borrowed, not re-allocated.
    prompt = [257, 1, 2, 3, 4, 5]
    engine.generate([prompt], SamplingParams(max_tokens=5))
    free_after_first = engine.alloc.free_pages
    engine.generate([prompt], SamplingParams(max_tokens=5))
    assert engine.alloc.free_pages == free_after_first
    assert engine.sequences == {}


def test_out_of_pages():
    cfg = EngineConfig(
        model="tiny-test", dtype=jnp.float32, tp=1,
        page_size=4, num_pages=2, max_pages_per_seq=2,
        max_batch_size=2, prefill_buckets=(16,),
    )
    small = Engine(cfg)
    sid = small.add_request([257, 1, 2, 3, 4, 5], SamplingParams(max_tokens=2))
    with pytest.raises(OutOfPages):
        small.add_request([257, 1, 2, 3, 4, 5], SamplingParams(max_tokens=2))
    small.finish(sid)
    # After freeing, admission succeeds again.
    sid2 = small.add_request([257, 9, 8, 7], SamplingParams(max_tokens=2))
    small.finish(sid2)


def test_constrained_mask_forbids_tokens(engine):
    prompt = [257, 42, 43, 44]
    free = ref_greedy(engine, prompt, 1)[0]

    def mask_fn(generated):
        m = np.ones((engine.model_cfg.vocab_size,), bool)
        m[free] = False  # forbid exactly the greedy choice
        return m

    sid = engine.add_request(prompt, SamplingParams(max_tokens=1), mask_fn=mask_fn)
    got = engine.finish(sid)
    assert got[0] != free


def test_stream_callback(engine):
    seen = []
    sid = engine.add_request(
        [257, 5, 6, 7], SamplingParams(max_tokens=4), stream=seen.append
    )
    while not engine.sequences[sid].done:
        engine.step([sid])
    toks = engine.finish(sid)
    assert seen == toks


def test_ttft_recorded(engine):
    sid = engine.add_request([257, 1], SamplingParams(max_tokens=1))
    seq_ttft = engine.sequences[sid].ttft_s
    engine.finish(sid)
    assert seq_ttft > 0


# -- block decode (decode_loop.decode_block via Engine.step_block) ----------
def test_step_block_matches_single_steps(engine):
    """The multi-step device loop must produce exactly the single-step
    greedy tokens (same programs, one dispatch)."""
    prompt = [257, 11, 22, 33, 44]
    sid1 = engine.add_request(prompt, SamplingParams(max_tokens=10))
    while not engine.sequences[sid1].done:
        engine.step([sid1])
    want = engine.finish(sid1)

    sid2 = engine.add_request(prompt, SamplingParams(max_tokens=10))
    while not engine.sequences[sid2].done:
        engine.step_block([sid2])
    got = engine.finish(sid2)
    assert got == want


def test_step_block_respects_max_tokens(engine):
    # max_tokens smaller than the block: the device budget must stop the row.
    prompt = [257, 3, 1, 4, 1, 5]
    sid = engine.add_request(prompt, SamplingParams(max_tokens=3))
    while not engine.sequences[sid].done:
        engine.step_block([sid])
    got = engine.finish(sid)
    assert len(got) == 3


def test_step_block_stop_string_rolls_back(engine):
    """A stop string hit mid-block truncates the accepted tokens and rolls
    the page accounting back; no pages may leak.

    The stop string is derived from the reference generation by scanning
    for the first token whose decoded text has not appeared earlier in the
    decoded output (the old hard-coded ``ref[1]`` assumed greedy tokens
    never repeat — weight-dependent, and false for the current seed, whose
    generation opens with a run of identical bytes)."""
    owned_before = engine.alloc.accounting()["owned"]
    prompt = [257, 11, 22, 33, 44]
    ref = ref_greedy(engine, prompt, 10)
    stop_txt = want_len = None
    for j in range(1, len(ref)):
        s = engine.tokenizer.decode([ref[j]])
        # Need a clean single-token text that first appears at step j:
        # replacement chars ("�", partial multi-byte sequences) also
        # render for OTHER incomplete tokens, so they cannot anchor a
        # first-occurrence scan.
        if not s or "�" in s:
            continue
        if s in engine.tokenizer.decode(ref[:j]):
            continue
        stop_txt, want_len = s, j + 1
        break
    assert stop_txt is not None, f"no usable stop token in {ref}"
    sid = engine.add_request(
        prompt, SamplingParams(max_tokens=10, stop=(stop_txt,))
    )
    while not engine.sequences[sid].done:
        engine.step_block([sid])
    seq = engine.sequences[sid]
    assert seq.finish_reason == "stop"
    got = engine.finish(sid)
    # The token matching the stop string ends generation.
    assert len(got) == want_len
    # No leak: every page is free, trie-donated (evictable), or owned by
    # someone else; this sequence holds nothing. (The old free_pages
    # equality only held when the donation was a single page — a donated
    # CHAIN's interior nodes are evictable-after-their-children, which
    # free_pages deliberately does not count.)
    acc = engine.alloc.accounting()
    assert acc["total"] == engine.cfg.num_pages
    assert acc["owned"] == owned_before


def test_step_block_batch_with_mixed_finishes(engine):
    p1 = [257, 10, 20, 30]
    p2 = [257, 99, 98, 97, 96, 95, 94]
    want1 = engine.generate([p1], SamplingParams(max_tokens=2))[0]
    want2 = engine.generate([p2], SamplingParams(max_tokens=9))[0]
    s1 = engine.add_request(p1, SamplingParams(max_tokens=2))
    s2 = engine.add_request(p2, SamplingParams(max_tokens=9))
    while not (engine.sequences[s1].done and engine.sequences[s2].done):
        engine.step_block([s1, s2])
    assert engine.finish(s1) == want1
    assert engine.finish(s2) == want2


def test_extend_upto_and_truncate_invariants():
    from opsagent_tpu.serving.kvcache import PageAllocator

    a = PageAllocator(num_pages=8, page_size=4, max_pages_per_seq=4)
    sid = a.allocate(6)           # 2 pages
    assert a.free_pages == 6
    got = a.extend_upto(sid, 16)  # wants 4 more pages, cap allows 2 more
    assert got == 10              # 2 slack in page 2 + 2 fresh pages
    assert a.length(sid) == 16
    assert a.free_pages == 4
    a.truncate(sid, 7)
    assert a.length(sid) == 7
    assert a.free_pages == 6      # back to 2 pages held
    a.free(sid)
    assert a.free_pages == 8


def test_step_block_mixed_masked_and_plain(engine):
    """A constrained row must not stop unconstrained rows from
    block-decoding, and both must advance correctly together."""
    prompt_m = [257, 42, 43, 44]
    prompt_p = [257, 11, 22, 33, 44]
    want_p = engine.generate([prompt_p], SamplingParams(max_tokens=8))[0]
    free = ref_greedy(engine, prompt_m, 1)[0]

    def mask_fn(generated):
        m = np.ones((engine.model_cfg.vocab_size,), bool)
        m[free] = False
        return m

    sm = engine.add_request(
        prompt_m, SamplingParams(max_tokens=4), mask_fn=mask_fn
    )
    sp = engine.add_request(prompt_p, SamplingParams(max_tokens=8))
    while not (engine.sequences[sm].done and engine.sequences[sp].done):
        out = engine.step_block([sm, sp])
        if sp in out and not engine.sequences[sp].done:
            assert len(out[sp]) >= 1
    got_m = engine.finish(sm)
    got_p = engine.finish(sp)
    assert got_p == want_p
    assert got_m[0] != free


def test_step_block_raising_stream_rolls_back_pages(engine):
    """A stream callback raising mid-block must still roll page accounting
    back to the accepted tokens (prefix-cache poisoning guard)."""
    free_before = engine.alloc.free_pages

    calls = []

    def boom(tok):
        calls.append(tok)
        if len(calls) == 3:
            raise RuntimeError("client went away")

    sid = engine.add_request(
        [257, 5, 6, 7], SamplingParams(max_tokens=12), stream=boom
    )
    with pytest.raises(RuntimeError, match="client went away"):
        while not engine.sequences[sid].done:
            engine.step_block([sid])
    seq = engine.sequences[sid]
    assert seq.done and seq.finish_reason == "error"
    # allocator length must equal the accepted token count invariant
    assert engine.alloc.length(sid) == seq.prompt_len + len(seq.tokens) - 1
    engine.finish(sid)
    assert engine.alloc.free_pages == free_before


def test_step_block_seq_ids_filter_only_advances_requested(engine):
    """With both sequences lane-seated, step_block([a]) must not advance b
    (its lane keeps the device carry but gets no budget)."""
    a = engine.add_request([257, 1, 2, 3], SamplingParams(max_tokens=12))
    b = engine.add_request([257, 4, 5, 6], SamplingParams(max_tokens=12))
    engine.step_block([a, b])  # seat both lanes
    engine.drain()             # settle the seating dispatch's tokens
    n_b = len(engine.sequences[b].tokens)
    for _ in range(6):
        if engine.sequences[a].done:
            break
        engine.step_block([a])
    engine.drain()
    assert len(engine.sequences[b].tokens) == n_b
    # b still advances fine afterwards.
    while not (engine.sequences[a].done and engine.sequences[b].done):
        engine.step_block([a, b])
    engine.finish(a)
    engine.finish(b)


def test_drain_merges_multi_block_pulls(engine):
    """drain() pulling several in-flight blocks for the same sequence must
    concatenate their tokens, not keep only the last block's."""
    want = engine.generate([[257, 8, 9]], SamplingParams(max_tokens=40))[0]
    sid = engine.add_request([257, 8, 9], SamplingParams(max_tokens=40))
    collected = list(engine.sequences[sid].tokens)  # admission's first token
    # Fill the pipeline without pulling everything, then drain.
    for _ in range(4):
        out = engine.step_block([sid])
        collected.extend(out.get(sid, []))
    collected.extend(engine.drain().get(sid, []))
    while not engine.sequences[sid].done:
        out = engine.step_block([sid])
        collected.extend(out.get(sid, []))
    collected.extend(engine.drain().get(sid, []))
    got = engine.finish(sid)
    assert got == want
    assert collected == want


def test_warmup_compiles_without_disturbing_state():
    """warmup() must leave page accounting and generation untouched: a
    warmed engine produces exactly what an unwarmed one does, and no pages
    leak (warmup writes through all-dropped page tables)."""
    cfg = EngineConfig(
        model="tiny-test", dtype=jnp.float32, tp=1, page_size=4,
        num_pages=32, max_pages_per_seq=8, max_batch_size=2,
        prefill_buckets=(16, 32),
    )
    cold = Engine(cfg)
    want = cold.generate([[257, 1, 2, 3]], SamplingParams(max_tokens=5))[0]

    warm = Engine(cfg)
    free_before = warm.alloc.free_pages
    dt = warm.warmup()
    assert dt > 0
    assert warm.alloc.free_pages == free_before
    assert warm.sequences == {}
    got = warm.generate([[257, 1, 2, 3]], SamplingParams(max_tokens=5))[0]
    assert got == want


def test_compilation_cache_dir_configured(tmp_path, monkeypatch):
    from opsagent_tpu.serving.engine import enable_compilation_cache

    monkeypatch.setenv("OPSAGENT_COMPILE_CACHE", str(tmp_path / "xla"))
    path = enable_compilation_cache()
    assert path == str(tmp_path / "xla")
    import os
    assert os.path.isdir(path)
    assert jax.config.jax_compilation_cache_dir == path


def test_prefill_chunks_interleave_with_decode():
    """VERDICT item 5: admitting a long prompt must not stall running
    decodes. begin_request/prefill_step split admission into bucket-sized
    chunks; a running stream advances between chunks."""
    cfg = EngineConfig(
        model="tiny-test", dtype=jnp.float32, tp=1, page_size=4,
        num_pages=64, max_pages_per_seq=16, max_batch_size=4,
        prefill_buckets=(8, 16), decode_block=4,
        prefix_cache=False,  # the oracle runs below would otherwise donate
                             # the long prompt's pages and skip its chunks
    )
    eng = Engine(cfg)
    # Oracle outputs via isolated synchronous runs.
    short = [257, 5, 6, 7]
    long_prompt = [257] + list(range(1, 40))   # 40 tokens = 3 chunks of <=16
    want_short = eng.generate([short], SamplingParams(max_tokens=12))[0]
    want_long = eng.generate([long_prompt], SamplingParams(max_tokens=4))[0]

    a = eng.add_request(short, SamplingParams(max_tokens=12))
    b = eng.begin_request(long_prompt, SamplingParams(max_tokens=4))
    assert not eng.sequences[b].tokens  # prefilling, not decodable yet

    chunks = 0
    decoded_between = 0
    while True:
        finished = eng.prefill_step(b)
        chunks += 1
        if finished:
            break
        if not eng.sequences[a].done:
            out = eng.step_block([a])
            decoded_between += sum(len(v) for v in out.values())
    assert chunks == 3               # 16 + 16 + 8
    eng.drain()
    # The running stream made progress while the long prompt admitted.
    assert decoded_between + len(eng.sequences[a].tokens) > 1
    while not (eng.sequences[a].done and eng.sequences[b].done):
        eng.step_block([a, b])
    assert eng.finish(a) == want_short
    assert eng.finish(b) == want_long


def test_scheduler_long_admission_keeps_decodes_flowing():
    """Scheduler-level: a long prompt admitting one chunk per tick must not
    block a concurrently running stream; both complete correctly."""
    from opsagent_tpu.serving.scheduler import Request, Scheduler

    cfg = EngineConfig(
        model="tiny-test", dtype=jnp.float32, tp=1, page_size=4,
        num_pages=96, max_pages_per_seq=24, max_batch_size=4,
        prefill_buckets=(8, 16), decode_block=4,
    )
    eng = Engine(cfg)
    short = [257, 9, 8, 7]
    long_prompt = [257] + list(range(1, 60))   # 60 tokens = 4 chunks
    want_short = eng.generate([short], SamplingParams(max_tokens=16))[0]
    want_long = eng.generate([long_prompt], SamplingParams(max_tokens=4))[0]

    sched = Scheduler(eng)
    sched.start()
    try:
        r1 = sched.submit(Request(short, SamplingParams(max_tokens=16)))
        r2 = sched.submit(Request(long_prompt, SamplingParams(max_tokens=4)))
        assert r1.done.wait(120) and r2.done.wait(120)
        assert not r1.error and not r2.error
        assert r1.tokens == want_short
        assert r2.tokens == want_long
    finally:
        sched.stop()


def test_batched_prefill_matches_sequential():
    """engine.prefill_batch (one dispatch for several admitting sequences,
    mixed fresh/partial states as prefix rows) must produce the same first
    tokens and generations as chunk-at-a-time prefill_step admission."""
    import jax.numpy as jnp

    from opsagent_tpu.serving.engine import Engine, EngineConfig
    from opsagent_tpu.serving.sampler import SamplingParams

    kw = dict(
        model="tiny-test", dtype=jnp.float32, tp=1, page_size=8,
        num_pages=256, max_pages_per_seq=32, max_batch_size=4,
        prefill_buckets=(8, 16),
    )
    prompts = [
        list(range(1, 13)),          # 12 tokens: chunks under bucket 16
        [7, 7, 8, 9],                # short fresh prompt
        list(range(20, 44)),         # 24 tokens: multiple chunks
    ]
    sampling = SamplingParams(temperature=0.0, max_tokens=6)

    want_eng = Engine(EngineConfig(**kw))
    want = want_eng.generate(prompts, sampling)

    eng = Engine(EngineConfig(**kw))
    sids = [eng.begin_request(p, sampling) for p in prompts]
    pending = set(sids)
    while pending:
        # Group is the caller's job; batch everything sharing the first
        # sequence's bucket, chunk the rest alone.
        first = sorted(pending)[0]
        bucket = eng.next_prefill_bucket(first)
        batch = [
            s for s in sorted(pending)
            if eng.next_prefill_bucket(s) == bucket
        ][: eng.cfg.prefill_batch]
        res = eng.prefill_batch(batch)
        pending -= {s for s, done in res.items() if done is True}
    live = {s for s in sids if not eng.sequences[s].done}
    while live:
        eng.step_block(sorted(live))
        live = {s for s in live if not eng.sequences[s].done}
    got = [eng.finish(s) for s in sids]
    assert got == want, (got, want)


def test_batched_prefill_isolates_bad_row():
    """A raising stream callback in one batched admission must fail ONLY
    that row: the other sequences keep their pages and first tokens."""
    import jax.numpy as jnp

    from opsagent_tpu.serving.engine import Engine, EngineConfig
    from opsagent_tpu.serving.sampler import SamplingParams

    eng = Engine(EngineConfig(
        model="tiny-test", dtype=jnp.float32, tp=1, page_size=8,
        num_pages=256, max_pages_per_seq=32, max_batch_size=4,
        prefill_buckets=(16,),
    ))
    free0 = eng.alloc.free_pages
    sampling = SamplingParams(temperature=0.0, max_tokens=4)

    def boom(_tok):
        raise RuntimeError("client went away")

    good = eng.begin_request([1, 2, 3, 4], sampling)
    bad = eng.begin_request([5, 6, 7], sampling, stream=boom)
    res = eng.prefill_batch([good, bad])
    assert res[good] is True
    assert isinstance(res[bad], RuntimeError)
    assert bad not in eng.sequences  # cleaned up
    assert len(eng.sequences[good].tokens) == 1  # first token sampled
    # Page accounting: only the good sequence holds pages now.
    while not eng.sequences[good].done:
        eng.step_block([good])
    eng.finish(good)
    assert eng.alloc.free_pages == free0
