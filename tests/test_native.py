"""Native C++ FSM matcher: build, parity with the pure-Python TokenFSM, and
graceful fallback when disabled."""

import os

import numpy as np
import pytest

from opsagent_tpu.native import NativeFSMTables, get_lib
from opsagent_tpu.serving.constrained import (
    TOOLPROMPT_SCHEMA,
    compile_regex,
    schema_to_regex,
)
from opsagent_tpu.serving.tokenizer import ByteTokenizer

native_available = get_lib() is not None

pytestmark = pytest.mark.skipif(
    not native_available, reason="g++/native build unavailable"
)


@pytest.fixture(scope="module")
def dfa():
    return compile_regex(schema_to_regex(TOOLPROMPT_SCHEMA))


@pytest.fixture(scope="module")
def tok():
    return ByteTokenizer()


def _python_fsm(dfa, tok):
    """A TokenFSM with the native path forcibly disabled."""
    from opsagent_tpu.serving import constrained as c

    fsm = c.TokenFSM.__new__(c.TokenFSM)
    c.TokenFSM.__init__(fsm, dfa, [
        tok.token_bytes(t) for t in range(tok.vocab_size)
    ], tok.eos_id)
    fsm._native = None
    return fsm


def test_native_masks_match_python(dfa, tok):
    tb = [tok.token_bytes(t) for t in range(tok.vocab_size)]
    native = NativeFSMTables(dfa.next, dfa.accept, tb, tok.eos_id)
    py = _python_fsm(dfa, tok)
    assert native.num_states == dfa.num_states
    for state in range(dfa.num_states):
        np.testing.assert_array_equal(
            native.mask_for_state(state),
            py.mask_for_state(state),
            err_msg=f"state {state}",
        )


def test_native_advance_matches_python(dfa, tok):
    tb = [tok.token_bytes(t) for t in range(tok.vocab_size)]
    native = NativeFSMTables(dfa.next, dfa.accept, tb, tok.eos_id)
    py = _python_fsm(dfa, tok)
    rng = np.random.default_rng(0)
    state = dfa.start
    walked = 0
    while walked < 200:
        mask = py.mask_for_state(state)
        ids = np.flatnonzero(mask)
        if not len(ids) or (len(ids) == 1 and ids[0] == tok.eos_id):
            break
        choices = [i for i in ids if i != tok.eos_id]
        nxt_tok = int(rng.choice(choices))
        assert native.advance(state, nxt_tok) == py.advance(state, nxt_tok)
        state = py.advance(state, nxt_tok)
        walked += 1
    assert walked > 10  # the walk actually exercised transitions


def test_tokenfsm_uses_native_when_available(dfa, tok):
    from opsagent_tpu.serving.constrained import json_constraint

    c = json_constraint(tok, TOOLPROMPT_SCHEMA)
    assert c.fsm._native is not None
    mask = c([])
    assert mask[ord("{")]


def test_env_disable_falls_back(dfa, tok):
    from opsagent_tpu import native

    os.environ["OPSAGENT_NATIVE"] = "0"
    try:
        assert native.get_lib() is None
    finally:
        os.environ.pop("OPSAGENT_NATIVE", None)


def test_dead_state_mask_is_empty(dfa, tok):
    tb = [tok.token_bytes(t) for t in range(tok.vocab_size)]
    native = NativeFSMTables(dfa.next, dfa.accept, tb, tok.eos_id)
    assert not native.mask_for_state(-1).any()
    assert native.advance(-1, 5) == -1
