"""Hierarchical KV cache (serving/offload): host pool semantics, allocator
spill/promote invariants, bit-exact device round trips (fp and int8-
quantized pages), the park-mid-conversation greedy-equivalence acceptance
gate (with zero post-warmup compiles on the restore path), tool-time
parking through the stack/agent surface, the re-prefill fallback anomaly,
and eviction under concurrent writers.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from opsagent_tpu import obs
from opsagent_tpu.serving.engine import Engine, EngineConfig
from opsagent_tpu.serving.kvcache import PageAllocator
from opsagent_tpu.serving.offload.pool import HostPagePool, tree_nbytes
from opsagent_tpu.serving.sampler import SamplingParams
from opsagent_tpu.serving.scheduler import Request, Scheduler

BASE = dict(
    model="tiny-test", dtype=jnp.float32, tp=1, page_size=4,
    num_pages=64, max_pages_per_seq=16, max_batch_size=4,
    prefill_buckets=(16, 32), decode_block=4, seed=0,
)

# Process-wide real-compile counter (the same monitoring event the compile
# watchdog consumes; never fires on jit-cache hits).
_COMPILES: list[str] = []


def _on_event(name: str, *a, **kw) -> None:
    if name == "/jax/core/compile/backend_compile_duration":
        _COMPILES.append(name)


jax.monitoring.register_event_duration_secs_listener(_on_event)


def _page_tree(value: float, page_size: int = 4) -> dict:
    return {
        "k": np.full((2, page_size, 1, 8), value, np.float32),
        "v": np.full((2, page_size, 1, 8), value, np.float32),
    }


# -- host pool ----------------------------------------------------------------
class TestHostPagePool:
    def test_put_match_chain_walk(self):
        pool = HostPagePool(page_size=4, capacity_bytes=1 << 20)
        toks = list(range(100, 112))  # 3 full pages
        for i in range(3):
            assert pool.put(toks[: (i + 1) * 4], _page_tree(float(i)))
        got = pool.match(toks)
        assert len(got) == 3
        assert [float(e.data["k"][0, 0, 0, 0]) for e in got] == [0.0, 1.0, 2.0]
        # start_page skips pages the HBM trie already served.
        assert len(pool.match(toks, start_page=1)) == 2
        assert len(pool.match(toks, start_page=1, max_pages=1)) == 1
        # A divergent history shares no chain.
        assert pool.match([1, 2, 3, 4, 5, 6, 7, 8]) == []

    def test_mid_chain_miss_stops_walk(self):
        pool = HostPagePool(page_size=4, capacity_bytes=1 << 20)
        toks = list(range(40, 52))
        pool.put(toks[:4], _page_tree(0.0))
        pool.put(toks[:12], _page_tree(2.0))  # page 3 present, page 2 absent
        assert len(pool.match(toks)) == 1  # walk stops at the gap

    def test_unaligned_and_empty_rejected(self):
        pool = HostPagePool(page_size=4, capacity_bytes=1 << 20)
        assert not pool.put([1, 2, 3], _page_tree(0.0))
        assert not pool.put([], _page_tree(0.0))

    def test_lru_drop_on_overflow_and_byte_accounting(self):
        one = tree_nbytes(_page_tree(0.0))
        pool = HostPagePool(page_size=4, capacity_bytes=3 * one)
        chains = []
        for i in range(3):
            toks = [200 + i] * 4
            chains.append(toks)
            assert pool.put(toks, _page_tree(float(i)))
        assert pool.used_bytes == 3 * one
        # Refresh chain 0's recency; inserting a 4th must drop chain 1.
        assert pool.match(chains[0])
        assert pool.put([300] * 4, _page_tree(9.0))
        assert pool.used_bytes == 3 * one
        assert pool.drops == 1
        assert pool.match(chains[0]) and not pool.match(chains[1])

    def test_oversized_page_rejected(self):
        pool = HostPagePool(page_size=4, capacity_bytes=16)
        assert not pool.put([1] * 4, _page_tree(0.0))
        assert pool.rejects == 1 and pool.used_bytes == 0

    def test_env_capacity(self, monkeypatch):
        monkeypatch.setenv("OPSAGENT_KV_HOST_POOL_BYTES", "12345")
        assert HostPagePool(page_size=4).capacity_bytes == 12345
        monkeypatch.setenv("OPSAGENT_KV_HOST_POOL_BYTES", "junk")
        assert HostPagePool(page_size=4).capacity_bytes == 1 << 30

    def test_eviction_under_8_concurrent_writers(self):
        one = tree_nbytes(_page_tree(0.0))
        pool = HostPagePool(page_size=4, capacity_bytes=8 * one)
        errors: list[BaseException] = []

        def writer(tid: int) -> None:
            try:
                for i in range(40):
                    toks = [tid * 1000 + i] * 4
                    pool.put(toks, _page_tree(float(tid)))
                    pool.match(toks)
                    if i % 7 == 0:
                        pool.drop_chain(toks)
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        threads = [
            threading.Thread(target=writer, args=(t,)) for t in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
            assert not t.is_alive()
        assert errors == []
        st = pool.stats()
        # The byte bound held throughout (checked at rest; enforcement is
        # under the same lock as every mutation).
        assert st["bytes"] <= pool.capacity_bytes
        assert st["pages"] * one == st["bytes"]


# -- allocator hooks ----------------------------------------------------------
def test_allocator_spill_hook_fires_with_full_chains():
    alloc = PageAllocator(num_pages=8, page_size=4, max_pages_per_seq=8)
    spilled: list[tuple[int, list[int]]] = []
    alloc.set_spill(lambda page, chain: spilled.append((page, chain)))
    toks = list(range(1, 25))  # 24 tokens = 6 pages
    sid = alloc.allocate(len(toks))
    alloc.free(sid, tokens=toks)  # donate the chain
    # Squeeze: a fresh 6-page allocation must evict trie leaves, spilling
    # each with its FULL page-aligned token prefix.
    sid2 = alloc.allocate(24)
    assert spilled, "eviction did not spill"
    for page, chain in spilled:
        assert len(chain) % 4 == 0 and len(chain) > 0
        assert chain == toks[: len(chain)]
    # A raising spill hook must not break eviction: park the free list in
    # one live allocation, then force the remaining trie pages out.
    alloc.free(sid2)
    alloc.set_spill(lambda *_: (_ for _ in ()).throw(RuntimeError("boom")))
    sid3 = alloc.allocate(len(alloc._free) * 4)
    before = alloc.evictions
    sid4 = alloc.allocate(8)  # must evict through the raising hook
    assert alloc.evictions > before
    alloc.free(sid3)
    alloc.free(sid4)
    assert alloc.accounting()["total"] == 8


def test_allocator_promote_prefix_registers_and_conserves():
    alloc = PageAllocator(num_pages=16, page_size=4, max_pages_per_seq=8)
    toks = list(range(50, 66))  # 16 tokens = 4 pages
    sid = alloc.allocate(len(toks))
    assert alloc.accounting()["owned"] == 4
    promoted = alloc.promote_prefix(sid, toks[:12])  # 3 full pages
    assert promoted == 3
    acc = alloc.accounting()
    assert acc["total"] == 16 and acc["owned"] == 1 and acc["trie"] == 3
    # Concurrent admission hits the promoted chain.
    hit = alloc.match_prefix(toks[:12])
    assert len(hit) == 3
    sid2 = alloc.allocate(13, prefix_pages=hit)
    assert alloc.accounting()["total"] == 16
    # Frees in either order keep conservation and release everything.
    alloc.free(sid, tokens=toks)
    alloc.free(sid2)
    acc = alloc.accounting()
    assert acc["total"] == 16 and acc["owned"] == 0


def test_allocator_evict_chain_stops_at_referenced_pages():
    alloc = PageAllocator(num_pages=16, page_size=4, max_pages_per_seq=8)
    toks = list(range(10, 26))
    sid = alloc.allocate(len(toks))
    alloc.free(sid, tokens=toks)
    chain = alloc.match_prefix(toks)
    assert len(chain) == 4
    # A live borrower pins the first two pages.
    sid2 = alloc.allocate(9, prefix_pages=chain[:2])
    n = alloc.evict_chain(chain)
    assert n == 2  # only the unreferenced tail fell
    alloc.free(sid2)
    assert alloc.accounting()["total"] == 16


# -- device round trips -------------------------------------------------------
def _run_to_done(eng, sid):
    while not eng.sequences[sid].done:
        eng.step_block([sid])


def _gather_pages_host(eng, pages):
    """Host numpy copy of the given device pages, one tree per page."""
    out = []
    for p in pages:
        out.append(jax.tree_util.tree_map(
            lambda leaf: np.asarray(leaf[:, p]), eng.cache
        ))
    return out


@pytest.mark.parametrize("kvq", ["", "int8"])
def test_park_restore_round_trip_bit_exact(kvq):
    """device->host->device through the pool must reproduce the KV pages
    BIT FOR BIT — fp32 pages and int8+scale quantized pages alike."""
    eng = Engine(EngineConfig(offload=True, kv_quantize=kvq, **BASE))
    prompt = [257, 72, 101, 108, 108, 111, 44, 32, 119]
    sid = eng.add_request(prompt, SamplingParams(max_tokens=7))
    _run_to_done(eng, sid)
    out1 = eng.finish(sid)
    hist = prompt + out1
    chain = eng.alloc.match_prefix(hist)
    assert chain, "nothing donated to the trie"
    before = _gather_pages_host(eng, chain)
    parked = eng.park_chain(hist)
    assert parked == len(chain) * eng.cfg.page_size
    eng.offload_flush()
    assert eng.offload.pool.num_pages >= len(chain)
    # Re-admit the grown history: the pages come back via the host pool.
    prompt2 = hist + [32, 110, 111, 119]
    sid2 = eng.begin_request(prompt2, SamplingParams(max_tokens=4))
    assert eng._prefilling[sid2] >= len(chain) * eng.cfg.page_size
    restored_pages = eng.alloc.pages_of(sid2)[: len(chain)]
    after = _gather_pages_host(eng, restored_pages)
    for b, a in zip(before, after):
        for lb, la in zip(
            jax.tree_util.tree_leaves(b), jax.tree_util.tree_leaves(a)
        ):
            np.testing.assert_array_equal(lb, la)
    while not eng.prefill_step(sid2):
        pass
    _run_to_done(eng, sid2)
    eng.finish(sid2)
    assert eng.alloc.accounting()["total"] == eng.cfg.num_pages


@pytest.mark.parametrize("kvq", ["", "int8"])
def test_parked_session_matches_never_offloaded_greedy(kvq):
    """The tentpole acceptance gate: a session parked mid-conversation and
    restored must produce exactly the greedy tokens of one that was never
    offloaded — fp AND int8-quantized caches — with ZERO post-warmup XLA
    compiles on the restore path."""
    # 8 pages: A's decode residency (5 pages) leaves too few for B's
    # 5-page admission — the parking policy MUST engage for B to admit.
    kw = dict(BASE, num_pages=8, max_pages_per_seq=8,
              prefill_buckets=(8, 16), mixed_batching=False)
    prompt_a = [257, 3, 1, 4, 1, 5, 9, 2, 6]   # 9 tokens
    prompt_b = [257] + list(range(60, 76))     # 17 tokens: 5 pages
    budget_a = 10

    ref = Engine(EngineConfig(kv_quantize=kvq, **kw))
    want = ref.generate([prompt_a], SamplingParams(max_tokens=budget_a))[0]

    eng = Engine(EngineConfig(offload=True, kv_quantize=kvq, **kw))
    eng.warmup("sessions")
    n0 = len(_COMPILES)
    sched = Scheduler(eng)  # driven manually: deterministic interleaving
    req_a = Request(list(prompt_a), SamplingParams(max_tokens=budget_a))
    sched.submit(req_a)
    sched._drain_queue()
    sched._try_admit()
    while sched._prefilling:
        sched._advance_prefill()
    assert req_a.seq_id in sched._running
    # A generates a few tokens, then stalls (a slow client, a cold
    # session): B's admission cannot fit and parks A to the host pool.
    for _ in range(2):
        eng.step_block(sorted(sched._running))
    eng.drain()
    req_b = Request(list(prompt_b), SamplingParams(max_tokens=4))
    sched.submit(req_b)
    sched._drain_queue()
    sched._try_admit()
    assert req_a.parked, "pressure parking did not engage"
    assert req_a in sched._waiting
    assert req_a.generated_prefix, "no tokens salvaged at park"
    assert req_b.seq_id is not None
    parks = [e for e in obs.flight.get_recorder().snapshot(kind="park")
             if e.get("trigger") == "pressure"]
    assert parks
    # Run B to completion and reap it.
    while sched._prefilling:
        sched._advance_prefill()
    while any(
        not eng.sequences[s].done for s in sched._running
        if s in eng.sequences
    ):
        eng.step_block(sorted(sched._running))
    eng.drain()
    sched._reap()
    assert req_b.done.is_set() and not req_b.error
    # A comes back: the admission restores its pages from the host pool.
    sched._try_admit()
    assert req_a.seq_id is not None, req_a.error
    restores = obs.flight.get_recorder().snapshot(kind="restore")
    assert restores, "re-admission did not restore from the host pool"
    while sched._prefilling:
        sched._advance_prefill()
    while any(
        not eng.sequences[s].done for s in sched._running
        if s in eng.sequences
    ):
        eng.step_block(sorted(sched._running))
    eng.drain()
    sched._reap()
    assert req_a.done.is_set() and not req_a.error
    assert req_a.tokens == want, (
        f"parked+restored {req_a.tokens} != uninterrupted {want}"
    )
    assert len(_COMPILES) == n0, (
        f"{len(_COMPILES) - n0} post-warmup compiles on the park/restore "
        f"path"
    )


def test_restore_fallback_reprefill_is_anomaly_and_still_correct():
    """Host-pool entries dropped under the byte bound: a parked session's
    comeback must fall back to re-prefill (correctness), count the
    fallback, and ring-dump a restore_reprefill anomaly (visibility)."""
    eng = Engine(EngineConfig(offload=True, **BASE))
    ref = Engine(EngineConfig(**BASE))
    prompt = [257, 8, 6, 7, 5, 3, 0, 9]
    want = ref.generate([prompt], SamplingParams(max_tokens=6))[0]
    sid = eng.add_request(prompt, SamplingParams(max_tokens=6))
    _run_to_done(eng, sid)
    out1 = eng.finish(sid)
    assert out1 == want
    hist = prompt + out1
    assert eng.park_chain(hist) > 0
    eng.offload_flush()
    eng.offload.pool.clear()  # the LRU bound dropped everything
    n_fb0 = obs.get_registry().snapshot().get(
        "opsagent_offload_restore_fallbacks_total", 0.0
    )
    sid2 = eng.begin_request(
        hist + [1, 2], SamplingParams(max_tokens=4), expect_restore=True
    )
    assert eng._prefilling[sid2] == 0  # nothing restored: full re-prefill
    anomalies = [
        e for e in obs.flight.get_recorder().snapshot(kind="anomaly")
        if e.get("reason") == "restore_reprefill"
    ]
    assert anomalies, "fallback did not trigger the anomaly"
    snap = obs.get_registry().snapshot()
    assert snap.get(
        "opsagent_offload_restore_fallbacks_total", 0.0
    ) == n_fb0 + 1
    while not eng.prefill_step(sid2):
        pass
    _run_to_done(eng, sid2)
    eng.finish(sid2)
    assert eng.alloc.accounting()["total"] == eng.cfg.num_pages


def test_tool_time_parking_via_stack_and_agent_signal():
    """ServingStack.park / api.park_session: the tool-exec signal from the
    agent loop parks the session's chain (HBM freed, host pool filled) and
    the next turn's admission restores it."""
    from opsagent_tpu.serving.api import (
        ServingStack, _stacks, install_stack, park_session,
    )

    kw = dict(BASE, num_pages=256, max_pages_per_seq=64,
              prefill_buckets=(32, 64, 128))
    stack = ServingStack(Engine(EngineConfig(offload=True, **kw)))
    install_stack("tiny-park", stack)
    try:
        messages = [
            {"role": "system", "content": "park test"},
            {"role": "user", "content": "hello world, this is turn one"},
        ]
        resp = stack.chat_completion(
            {"messages": messages, "max_tokens": 8, "temperature": 0}
        )
        messages.append({
            "role": "assistant",
            "content": resp["choices"][0]["message"]["content"] or "",
        })
        # The tpu:// scheme routing the agent loop uses (case-insensitive).
        parked = park_session("tpu://Tiny-Park", messages)
        assert parked > 0
        stack.engine.offload_flush()
        assert stack.engine.offload.pool.num_pages > 0
        parks = obs.flight.get_recorder().snapshot(kind="park")
        assert any(p.get("trigger") == "tool" for p in parks)
        # Unknown model name: safe no-op.
        assert park_session("tpu://no-such-stack", messages) == 0
        # Next turn restores instead of re-prefilling.
        messages.append({"role": "user", "content": "and now turn two"})
        stack.chat_completion(
            {"messages": messages, "max_tokens": 4, "temperature": 0}
        )
        restores = obs.flight.get_recorder().snapshot(kind="restore")
        assert restores, "turn 2 did not restore the parked chain"
        snap = obs.get_registry().snapshot()
        assert snap.get(
            "opsagent_offload_reprefill_avoided_tokens_total", 0.0
        ) > 0
    finally:
        stack.close()
        _stacks.pop("tiny-park", None)


def test_accounting_exposes_host_pool_and_metrics():
    eng = Engine(EngineConfig(offload=True, **BASE))
    prompt = [257, 5, 6, 7, 8, 9, 10, 11]
    sid = eng.add_request(prompt, SamplingParams(max_tokens=5))
    _run_to_done(eng, sid)
    out = eng.finish(sid)
    eng.park_chain(prompt + out)
    eng.offload_flush()
    acc = eng.alloc.accounting()
    assert acc["host_pool_pages"] == eng.offload.pool.num_pages > 0
    assert acc["host_pool_bytes"] == eng.offload.pool.used_bytes > 0
    assert acc["host_pool_capacity_bytes"] == eng.offload.pool.capacity_bytes
    text = obs.metrics_text()
    assert "opsagent_kv_host_pool_bytes" in text
    assert 'opsagent_offload_pages_total{dir="out"}' in text


def test_offload_disabled_paths_are_noops():
    eng = Engine(EngineConfig(**BASE))
    assert eng.offload is None
    assert eng.park_chain([1, 2, 3, 4]) == 0
    assert eng.offload_flush() == 0
    with pytest.raises(RuntimeError):
        eng.park_sequence(0)
