"""Double-buffered Pallas quantized matmul vs the XLA dequant oracle.

Interpret mode on CPU (the TPU-lowered path shares the trace), mirroring
tests/test_pallas_paged.py: kernel-level parity for int8 and packed-int4
weights — including the ragged last contraction tile and the
contraction-smaller-than-group edge — the column-parallel shard_map form,
and the engine-level acceptance gates: ``weight_stream="pallas-dma"``
must produce BYTE-IDENTICAL greedy output to the xla weight stream
through the mixed hot path with zero post-warmup compiles, and must fall
back to xla whenever its gates (quantized weights, tp == 1) trip.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from opsagent_tpu.models.quant import (
    QuantizedLinear,
    QuantizedLinear4,
    quantize_weight,
    quantize_weight4,
)
from opsagent_tpu.ops.quant_matmul_pallas import (
    quant_matmul_pallas,
    quant_matmul_pallas_tp,
    supports,
)

# Count real XLA compiles process-wide (same listener discipline as
# tests/test_mixed_batching.py): fires once per backend compile, never
# on jit-cache hits; tests diff around the window they care about.
_COMPILES: list[str] = []


def _on_event(name: str, *a, **kw) -> None:
    if name == "/jax/core/compile/backend_compile_duration":
        _COMPILES.append(name)


jax.monitoring.register_event_duration_secs_listener(_on_event)


def _oracle(x, w):
    """The XLA path's elementwise math (llama._mm): dequantize, cast to
    the activation dtype, one long contraction."""
    return x @ w.dequantize().astype(x.dtype)


def _assert_matches(got, ref, exact):
    """Single-tile contractions share the oracle's reduction order ->
    exact equality; multi-tile streams sum f32 partials per tile, the
    same fidelity class as the paged Pallas kernels vs the XLA gather."""
    if exact:
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    else:
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=1e-3, atol=1e-3
        )


# -- int8 kernel --------------------------------------------------------------
@pytest.mark.parametrize(
    "T,In,Out,exact",
    [
        (8, 256, 384, True),     # exactly one contraction tile
        (16, 300, 256, False),   # ragged last tile (clamp + re-read zero)
        (4, 64, 128, True),      # contraction smaller than IN_TILE
        (32, 512, 512, False),   # multi-tile contraction
        (1, 256, 128, True),     # single decode row
    ],
)
def test_int8_matches_oracle(T, In, Out, exact):
    """Tile-by-tile dequant mirrors the oracle's elementwise math:
    single-tile shapes are bit-exact, multi-tile shapes differ only by
    f32 reduction order."""
    rng = np.random.default_rng(0)
    w = quantize_weight(
        jnp.asarray(rng.standard_normal((In, Out)), jnp.float32)
    )
    x = jnp.asarray(rng.standard_normal((T, In)), jnp.float32)
    got = quant_matmul_pallas(x, w, interpret=True)
    _assert_matches(got, _oracle(x, w), exact)


def test_int8_bf16_activations():
    """bf16 activations keep the oracle's cast discipline (dequantized
    tile cast to bf16 BEFORE the dot) — still elementwise identical."""
    rng = np.random.default_rng(1)
    w = quantize_weight(
        jnp.asarray(rng.standard_normal((256, 256)), jnp.float32)
    )
    x = jnp.asarray(rng.standard_normal((8, 256)), jnp.bfloat16)
    got = quant_matmul_pallas(x, w, interpret=True)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(got, np.float32), np.asarray(_oracle(x, w), np.float32)
    )


# -- packed int4 kernel -------------------------------------------------------
@pytest.mark.parametrize(
    "T,In,Out,group,exact",
    [
        (8, 256, 384, 128, False),   # two scale groups (two DMA steps)
        (16, 256, 256, 256, True),   # single group = whole contraction
        (4, 64, 128, 128, True),     # contraction < requested group
        (32, 512, 512, 128, False),  # many groups, many out tiles
    ],
)
def test_int4_matches_oracle(T, In, Out, group, exact):
    rng = np.random.default_rng(2)
    w = quantize_weight4(
        jnp.asarray(rng.standard_normal((In, Out)), jnp.float32),
        group=group,
    )
    x = jnp.asarray(rng.standard_normal((T, In)), jnp.float32)
    got = quant_matmul_pallas(x, w, interpret=True)
    _assert_matches(got, _oracle(x, w), exact)


def test_int4_nibble_order_against_manual_unpack():
    """The kernel's in-register unpack must reproduce quantize_weight4's
    packing exactly: low nibble = even contraction row, high = odd,
    arithmetic shifts sign-extending negatives."""
    rng = np.random.default_rng(3)
    In, Out = 32, 128
    w = quantize_weight4(
        jnp.asarray(rng.standard_normal((In, Out)), jnp.float32), group=In
    )
    # One-hot activations read out single dequantized rows.
    x = jnp.eye(In, dtype=jnp.float32)
    got = quant_matmul_pallas(x, w, interpret=True)
    np.testing.assert_array_equal(
        np.asarray(got), np.asarray(w.dequantize(), np.float32)
    )


# -- supports() / error surface ----------------------------------------------
def test_supports_gates():
    w8 = quantize_weight(jnp.ones((64, 128), jnp.float32))
    assert supports(w8)
    # Stacked/MoE 3D leaves stay on the XLA path.
    stacked = QuantizedLinear(
        jnp.zeros((2, 64, 128), jnp.int8), jnp.ones((2, 1, 128))
    )
    assert not supports(stacked)
    w4 = quantize_weight4(jnp.ones((64, 128), jnp.float32))
    assert supports(w4)
    # Odd scale group would split packed bytes across groups.
    odd = QuantizedLinear4(
        jnp.zeros((48, 64), jnp.int8), jnp.ones((32, 1, 64), jnp.float32)
    )
    assert not supports(odd)
    assert not supports(jnp.ones((64, 128)))


def test_rejects_bad_shapes():
    w = quantize_weight(jnp.ones((64, 128), jnp.float32))
    with pytest.raises(ValueError, match="In"):
        quant_matmul_pallas(jnp.ones((4, 32)), w, interpret=True)
    with pytest.raises(ValueError, match=r"\[T, In\]"):
        quant_matmul_pallas(jnp.ones((2, 4, 64)), w, interpret=True)
    stacked = QuantizedLinear(
        jnp.zeros((2, 64, 128), jnp.int8), jnp.ones((2, 1, 128))
    )
    with pytest.raises(ValueError, match="2D"):
        quant_matmul_pallas(jnp.ones((4, 64)), stacked, interpret=True)


# -- TP shard_map form --------------------------------------------------------
@pytest.mark.parametrize("quant", ["int8", "int4"])
def test_tp_column_parallel_matches_oracle(quant):
    """tp=2 mesh, weight sharded on the OUTPUT axis, x replicated: each
    shard streams only its own columns; concatenated output must equal
    the unsharded oracle exactly."""
    from opsagent_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices")
    mesh = make_mesh(tp=2, dp=1, sp=1, devices=jax.devices()[:2])
    rng = np.random.default_rng(4)
    dense = jnp.asarray(rng.standard_normal((256, 256)), jnp.float32)
    w = (
        quantize_weight(dense) if quant == "int8"
        else quantize_weight4(dense, group=128)
    )
    x = jnp.asarray(rng.standard_normal((8, 256)), jnp.float32)
    got = quant_matmul_pallas_tp(x, w, mesh, interpret=True)
    # int8 shards see one contraction tile (exact); int4 has two scale
    # groups per shard, so only reduction order differs.
    _assert_matches(got, _oracle(x, w), exact=(quant == "int8"))


# -- engine acceptance gates --------------------------------------------------
ENGINE_BASE = dict(
    model="tiny-test", dtype=jnp.float32, tp=1, page_size=4,
    num_pages=128, max_pages_per_seq=24, max_batch_size=3,
    prefill_buckets=(8, 16), decode_block=4,
    mixed_batching=True, mixed_buckets=(4, 8, 16), max_step_tokens=32,
    async_depth=1, warmup=False,
)

PROMPTS = [
    [257] + list(range(1, 12)),
    [257] + [5, 9, 2, 8, 1, 7, 3, 3, 4, 6, 2, 9, 8, 1, 5, 5, 2],
    [257, 4, 4, 2],
]


def _run_mixed(eng, level):
    """Chunked mixed admission + interleaved decode to completion, with
    the zero-post-warmup-compile assertion around the serving window."""
    from opsagent_tpu.serving.sampler import SamplingParams

    eng.warmup(level)
    sampling = SamplingParams(temperature=0.0, max_tokens=8)
    n0 = len(_COMPILES)
    sids: list[int] = []
    for prompt in PROMPTS:
        b = eng.begin_request(prompt, sampling)
        while b in eng._prefilling:
            done, total = eng.prefill_progress(b)
            lanes = [s for s in sids if not eng.sequences[s].done][:2]
            eng.step_mixed(lanes, {b: min(total - done, 16)})
        sids.append(b)
    live = [s for s in sids if not eng.sequences[s].done]
    while live:
        eng.step_mixed(live, {})
        live = [s for s in live if not eng.sequences[s].done]
    outs = [eng.finish(s) for s in sids]
    assert len(_COMPILES) == n0, (
        f"{len(_COMPILES) - n0} post-warmup compiles with "
        f"weight_stream={eng.weight_stream_impl}"
    )
    return outs


@pytest.mark.parametrize(
    "quant,level",
    [
        ("int8", "sessions"),     # ffwd + full mixed family warmed
        ("int4", "bench-mixed"),  # the sweep's minimal mixed-only level
    ],
)
def test_engine_weight_streams_byte_identical(monkeypatch, quant, level):
    """The tentpole acceptance gate: pallas-dma weight streaming through
    the REAL mixed hot path (chunked admission + interleaved decode, the
    exact step_mixed composition serving runs) produces byte-identical
    greedy output to the xla weight stream, with zero post-warmup
    compiles on both engines."""
    from opsagent_tpu.serving.engine import Engine, EngineConfig

    monkeypatch.setenv("OPSAGENT_PALLAS_INTERPRET", "1")
    outs = {}
    for ws in ("xla", "pallas-dma"):
        eng = Engine(EngineConfig(
            quantize=quant, weight_stream=ws, **ENGINE_BASE
        ))
        assert eng.weight_stream_impl == ws
        assert eng.impl_info()["weight_stream"] == ws
        outs[ws] = _run_mixed(eng, level)
    assert outs["xla"] == outs["pallas-dma"], outs


def test_engine_weight_stream_env_knob(monkeypatch):
    """OPSAGENT_WEIGHT_STREAM is the deploy-side spelling of the config
    field; the config field wins when both are set."""
    from opsagent_tpu.serving.engine import Engine, EngineConfig

    monkeypatch.setenv("OPSAGENT_PALLAS_INTERPRET", "1")
    monkeypatch.setenv("OPSAGENT_WEIGHT_STREAM", "pallas-dma")
    eng = Engine(EngineConfig(quantize="int8", **ENGINE_BASE))
    assert eng.weight_stream_impl == "pallas-dma"


def test_engine_falls_back_without_quantized_weights(monkeypatch):
    """pallas-dma weight streaming needs narrow storage to stream;
    full-precision engines resolve to xla instead of dying."""
    from opsagent_tpu.serving.engine import Engine, EngineConfig

    eng = Engine(EngineConfig(weight_stream="pallas-dma", **ENGINE_BASE))
    assert eng.weight_stream_impl == "xla"
    assert eng.impl_info()["weight_stream"] == "xla"


def test_engine_falls_back_on_tp(monkeypatch):
    """Sharded engines keep the XLA weight path until the row-parallel
    psum epilogue is wired (the resolution gate, not a crash)."""
    from opsagent_tpu.serving.engine import Engine, EngineConfig

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices")
    cfg = dict(ENGINE_BASE, tp=2)
    eng = Engine(EngineConfig(
        quantize="int8", weight_stream="pallas-dma", **cfg
    ))
    assert eng.weight_stream_impl == "xla"


def test_engine_rejects_unknown_weight_stream():
    from opsagent_tpu.serving.engine import Engine, EngineConfig

    with pytest.raises(ValueError, match="weight_stream"):
        Engine(EngineConfig(weight_stream="dma2", **ENGINE_BASE))


def test_attribution_reroutes_weight_bytes_under_prefetch():
    """weight_stream=pallas-dma moves the weight bytes to the
    weights_prefetch kind and prices the step at the OVERLAPPED roofline
    max(bytes/bw, flops/peak); the serial model is unchanged."""
    from opsagent_tpu.obs.attribution import Attribution

    kw = dict(
        num_params=1_000_000, num_layers=4, num_heads=8, num_kv_heads=4,
        head_dim=64, vocab_size=1000, quantize="int8",
    )
    serial = Attribution(**kw)
    overlap = Attribution(weight_stream="pallas-dma", **kw)
    cs = serial.cost(q_tokens=4, kv_read_tokens=100, kv_write_tokens=4)
    co = overlap.cost(q_tokens=4, kv_read_tokens=100, kv_write_tokens=4)
    assert cs["weights"] > 0 and cs["weights_prefetch"] == 0
    assert co["weights"] == 0 and co["weights_prefetch"] == cs["weights"]
    assert co["total"] == cs["total"]
    # Bytes-bound composition: overlapped floor equals the bytes floor.
    assert co["modeled_s"] == cs["modeled_s"]
    # Compute-bound composition: the FLOP term takes over.
    big = overlap.cost(q_tokens=100_000, attn_q_ctx=10_000_000)
    assert big["modeled_s"] > big["total"] / overlap.hbm_bytes_s
    assert big["modeled_s"] == pytest.approx(
        big["flops"] / overlap.peak_flops_s
    )
