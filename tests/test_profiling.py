"""Device-level profiling (utils/profiling.py): jax.profiler traces and
per-step device timings — SURVEY §5's TPU additions over the reference's
host-only timer registry (reference pkg/utils/perf.go:168-210)."""

import os

import jax
import jax.numpy as jnp

from opsagent_tpu.utils import profiling
from opsagent_tpu.utils.perf import get_perf_stats


def test_trace_noop_without_dir(monkeypatch):
    monkeypatch.delenv("OPSAGENT_PROFILE_DIR", raising=False)
    with profiling.trace():  # must not start a real trace
        jnp.ones((4,)).block_until_ready()


def test_trace_writes_capture(tmp_path, monkeypatch):
    logdir = tmp_path / "prof"
    with profiling.trace(str(logdir)):
        jax.jit(lambda x: x * 2)(jnp.ones((8, 8))).block_until_ready()
    files = [
        os.path.join(r, f) for r, _, fs in os.walk(logdir) for f in fs
    ]
    assert files, "jax.profiler trace produced no capture files"


def test_annotate_is_free_outside_trace():
    with profiling.annotate("unit-test-region"):
        pass


def test_device_timer_records_metric(monkeypatch):
    monkeypatch.setenv("OPSAGENT_DEVICE_TIMING", "1")
    perf = get_perf_stats()
    perf.reset()
    outs: list = []
    with profiling.device_timer("unit_step", outs):
        outs.append(jax.jit(lambda x: x + 1)(jnp.zeros((16,))))
    stats = perf.get_stats()
    assert "device.unit_step" in stats
    assert stats["device.unit_step"]["count"] == 1


def test_device_timer_noop_when_disabled(monkeypatch):
    monkeypatch.delenv("OPSAGENT_DEVICE_TIMING", raising=False)
    perf = get_perf_stats()
    perf.reset()
    with profiling.device_timer("disabled_step", []):
        pass
    assert "device.disabled_step" not in perf.get_stats()
