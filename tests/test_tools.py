"""Tests for the tool layer."""

import sys

import pytest

from opsagent_tpu.tools import ToolPrompt, ToolError, get_tools
from opsagent_tpu.tools.jq import jq, _split_input
from opsagent_tpu.tools.kubectl import filter_noise, _classify
from opsagent_tpu.tools.python_tool import python_repl
from opsagent_tpu.tools.trivy import trivy


def test_toolprompt_roundtrip():
    tp = ToolPrompt.from_json(
        '{"question": "q", "thought": "t", '
        '"action": {"name": "kubectl", "input": "get ns"}, '
        '"observation": "", "final_answer": ""}'
    )
    assert tp.action.name == "kubectl"
    tp.observation = "3 namespaces"
    d = tp.to_dict()
    assert d["action"]["input"] == "get ns"
    assert d["observation"] == "3 namespaces"
    again = ToolPrompt.from_json(tp.to_json())
    assert again.observation == "3 namespaces"


def test_toolprompt_tolerates_action_string():
    tp = ToolPrompt.from_json('{"action": "kubectl", "thought": "t"}')
    assert tp.action.name == "kubectl"


def test_toolprompt_tolerates_sloppy_json():
    tp = ToolPrompt.from_json(
        '```json\n{"thought": "multi\nline", "final_answer": "done",}\n```'
    )
    assert tp.final_answer == "done"
    assert tp.thought == "multi\nline"


def test_registry_contents():
    tools = get_tools()
    for name in ("kubectl", "python", "trivy", "jq", "search"):
        assert name in tools


def test_python_tool_runs():
    assert python_repl("print(21 * 2)") == "42"


def test_python_tool_error():
    with pytest.raises(ToolError):
        python_repl("raise RuntimeError('boom')")


def test_python_tool_uses_argv_not_shell():
    # Quotes and shell metacharacters must pass through untouched.
    out = python_repl("""print('he said "hi"; $(ls)')""")
    assert out == 'he said "hi"; $(ls)'


def test_jq_split_on_top_level_pipe_only():
    data, expr = _split_input('{"a": "x|y"} | .a')
    assert data == '{"a": "x|y"}'
    assert expr == ".a"


def test_jq_invalid_json():
    with pytest.raises(ToolError):
        jq("not json | .a")


def test_jq_no_pipe():
    with pytest.raises(ToolError):
        jq('{"a": 1}')


def test_jq_fallback_path_eval(monkeypatch):
    # Force the built-in evaluator even when a jq binary exists.
    from opsagent_tpu.tools import proc

    def no_jq(*a, **k):
        raise FileNotFoundError("jq")

    monkeypatch.setattr(proc, "run", no_jq)
    assert jq('{"a": {"b": [10, 20]}} | .a.b[1]') == "20"
    assert jq('{"items": [{"n": 1}, {"n": 2}]} | .items[].n') == "1\n2"
    assert jq('[1, 2, 3] | length') == "3"


def test_kubectl_classify():
    assert _classify("kubectl get pods") == "get"
    assert _classify("kubectl -n kube-system describe pod x") == "describe"
    assert _classify("kubectl logs x --tail=10") == "logs"


def test_kubectl_noise_filter():
    noisy = (
        "NAME   READY\n"
        "web-1  1/1\n"
        "E0307 12:00:00.123456 couldn't reach metrics server\n"
        "couldn't get current server API group list: timeout\n"
    )
    out = filter_noise(noisy)
    assert "web-1" in out
    assert "E0307" not in out
    assert "API group list" not in out


def test_trivy_strips_image_prefix(monkeypatch):
    from opsagent_tpu.tools import proc

    captured = {}

    def fake_run(argv, **kw):
        captured["argv"] = argv

        class R:
            returncode = 0
            stdout = "no vulns"
            stderr = ""

        return R()

    monkeypatch.setattr(proc, "run", fake_run)
    assert trivy("image nginx:1.25") == "no vulns"
    assert captured["argv"][:3] == ["trivy", "image", "nginx:1.25"]


def test_trivy_empty_image():
    with pytest.raises(ToolError):
        trivy("   ")
