"""Test configuration.

JAX tests run on a virtual 8-device CPU mesh: the env vars must be set before
jax initializes its backends (the tpu-native answer to testing multi-chip
sharding without a real pod slice; SURVEY.md section 4).
"""

import os

# Force the virtual 8-device CPU mesh. Env vars alone are NOT enough here:
# a TPU-plugin sitecustomize may import jax at interpreter boot (before this
# conftest), freezing jax_platforms from the image environment — so set the
# XLA flag env (read lazily at CPU-client creation) AND override the already-
# imported config.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

from opsagent_tpu.llm import client as llm_client  # noqa: E402
from opsagent_tpu import tools as tools_pkg  # noqa: E402
from opsagent_tpu.utils.globalstore import clear_globals  # noqa: E402
from opsagent_tpu.utils.perf import get_perf_stats  # noqa: E402


class ScriptedLLM:
    """A scripted fake chat provider: pops one canned reply per request.

    Replies may be strings (assistant content), dicts (full assistant
    messages, e.g. with tool_calls), or callables taking the request body.
    """

    def __init__(self, replies):
        self.replies = list(replies)
        self.requests = []

    def __call__(self, body):
        import copy

        self.requests.append(copy.deepcopy(body))
        if not self.replies:
            raise AssertionError("ScriptedLLM ran out of replies")
        r = self.replies.pop(0)
        if callable(r):
            r = r(body)
        message = r if isinstance(r, dict) else {"role": "assistant", "content": r}
        return {
            "id": "fake",
            "object": "chat.completion",
            "choices": [{"index": 0, "message": message, "finish_reason": "stop"}],
            "usage": {},
        }


@pytest.fixture
def scripted_llm():
    """Register a ScriptedLLM under the fake:// scheme; use model='fake://m'."""

    def _register(replies):
        fake = ScriptedLLM(replies)
        llm_client.register_provider("fake", lambda target: fake)
        return fake

    yield _register
    llm_client._provider_factories.pop("fake", None)


@pytest.fixture
def fake_tools():
    """Replace the tool registry with test doubles; restore afterwards."""
    saved = dict(tools_pkg.copilot_tools)

    def _install(mapping):
        tools_pkg.copilot_tools.clear()
        tools_pkg.copilot_tools.update(mapping)
        return mapping

    yield _install
    tools_pkg.copilot_tools.clear()
    tools_pkg.copilot_tools.update(saved)


@pytest.fixture(autouse=True)
def clean_state():
    clear_globals()
    get_perf_stats().reset()
    yield
    clear_globals()
    get_perf_stats().reset()
