"""Test configuration.

JAX tests run on a virtual 8-device CPU mesh: the env vars must be set before
jax initializes its backends (the tpu-native answer to testing multi-chip
sharding without a real pod slice; SURVEY.md section 4).
"""

import os

# Force the virtual 8-device CPU mesh. Env vars alone are NOT enough here:
# a TPU-plugin sitecustomize may import jax at interpreter boot (before this
# conftest), freezing jax_platforms from the image environment — so set the
# XLA flag env (read lazily at CPU-client creation) AND override the already-
# imported config.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Flight-recorder anomaly dumps (e.g. a slow first compile-laden TTFT
# crossing the 500 ms threshold) go to a throwaway dir, not the repo's
# logs/; tests that assert on dumps monkeypatch their own dir.
import tempfile  # noqa: E402

os.environ.setdefault(
    "OPSAGENT_FLIGHT_DIR", tempfile.mkdtemp(prefix="opsagent-flight-")
)

import pytest  # noqa: E402

from opsagent_tpu import obs  # noqa: E402
from opsagent_tpu.llm import client as llm_client  # noqa: E402
from opsagent_tpu import tools as tools_pkg  # noqa: E402
from opsagent_tpu.utils.globalstore import clear_globals  # noqa: E402
from opsagent_tpu.utils.perf import get_perf_stats  # noqa: E402


class ScriptedLLM:
    """A scripted fake chat provider: pops one canned reply per request.

    Replies may be strings (assistant content), dicts (full assistant
    messages, e.g. with tool_calls), or callables taking the request body.
    """

    def __init__(self, replies):
        self.replies = list(replies)
        self.requests = []

    def __call__(self, body):
        import copy

        self.requests.append(copy.deepcopy(body))
        if not self.replies:
            raise AssertionError("ScriptedLLM ran out of replies")
        r = self.replies.pop(0)
        if callable(r):
            r = r(body)
        message = r if isinstance(r, dict) else {"role": "assistant", "content": r}
        return {
            "id": "fake",
            "object": "chat.completion",
            "choices": [{"index": 0, "message": message, "finish_reason": "stop"}],
            "usage": {},
        }


@pytest.fixture
def scripted_llm():
    """Register a ScriptedLLM under the fake:// scheme; use model='fake://m'."""

    def _register(replies):
        fake = ScriptedLLM(replies)
        llm_client.register_provider("fake", lambda target: fake)
        return fake

    yield _register
    llm_client._provider_factories.pop("fake", None)


@pytest.fixture
def fake_tools():
    """Replace the tool registry with test doubles; restore afterwards."""
    saved = dict(tools_pkg.copilot_tools)

    def _install(mapping):
        tools_pkg.copilot_tools.clear()
        tools_pkg.copilot_tools.update(mapping)
        return mapping

    yield _install
    tools_pkg.copilot_tools.clear()
    tools_pkg.copilot_tools.update(saved)


def _reset_obs():
    # Observability isolation: clear the metric SAMPLES (instruments stay
    # registered), the trace ring, the flight-recorder ring, the SLO
    # watchdog's rate window, and the compile watchdog's warmed flag —
    # one test's engine warmup must not turn a later test's lazy compile
    # into a "post-warmup compile" anomaly dump.
    obs.get_registry().reset()
    obs.get_store().clear()
    obs.flight.get_recorder().reset()
    obs.flight.reset_compile_watchdog()
    obs.slo.get_watchdog().reset()
    obs.history.reset()
    obs.trace.reset_retention()
    # Fault injection is process-global: clear hit counters and unpin any
    # spec a test configured so chaos never leaks across tests.
    from opsagent_tpu.serving import faults as _faults

    _faults.reset()


@pytest.fixture(autouse=True)
def clean_state():
    clear_globals()
    get_perf_stats().reset()
    _reset_obs()
    yield
    clear_globals()
    get_perf_stats().reset()
    _reset_obs()


# -- fast/slow lanes ---------------------------------------------------------
# VERDICT r03 #6: the full suite cannot finish inside a 10-minute window
# single-process on a 1-core box. Tests measured >= ~8 s there (compile-
# heavy multi-device oracles, subprocess re-execs, in-tree training runs)
# carry the `slow` marker, so `-m "not slow"` is a fast smoke lane and
# CI can split lanes. Central list (nodeid substrings) rather than
# per-file decorators so the lane is auditable in one place; tests may
# also self-mark with @pytest.mark.slow (e.g. test_distributed).
SLOW_TESTS = (
    "test_training.py::test_graft_dryrun_multichip_8",
    "test_bench_harness.py::test_wedged_child_killed_and_fallback_lands",
    "test_bench_harness.py::test_tiny_budget_goes_straight_to_fallback",
    "test_bench_harness.py::test_orchestrated_cpu_ends_with_headline_json",
    "test_bench_harness.py::test_agent_mode_reports_per_turn_ttft_and_hit_rate",
    "test_bench_harness.py::test_agent_conveyor_mode_reports_ab_numbers",
    "test_conveyor.py::test_park_at_launch_frees_pages_for_readmission",
    "test_conveyor.py::test_trained_agent_e2e_gantt_shows_overlap",
    "test_trained_agent.py::test_train_serve_agent_roundtrip",
    "test_pipeline.py::test_pp2_",
    "test_pipeline.py::test_pp_remat_matches",
    "test_real_checkpoint.py::test_agent_loop_from_saved_checkpoint",
    "test_train_checkpoint.py::test_save_restore_roundtrip",
    "test_fanout.py::test_cluster_audit_acceptance_200",
    "test_engine.py::test_long_generation_crosses_pages",
    "test_engine.py::test_generate_matches_oracle",
    "test_engine.py::test_warmup_compiles_without_disturbing_state",
    "test_serving_api.py::test_tpu_scheme_lazy_registration_fresh_process",
    "test_constrained.py::TestEngineWiring::test_response_format_constrains",
    "test_speculative.py::test_speculative_matches_vanilla_greedy",
    "test_moe.py::test_sharded_moe_training_step",
    "test_ring_attention.py::test_ring_gradients_flow",
    "test_tool_choice.py::test_required_constrains_to_listed_tools",
    "test_quant.py::test_quantized_forward_close_to_fp",
)


def pytest_collection_modifyitems(config, items):
    for item in items:
        if any(s in item.nodeid for s in SLOW_TESTS):
            item.add_marker(pytest.mark.slow)
