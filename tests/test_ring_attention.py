"""Ring attention (context parallelism over sp): numerical equivalence with
the dense causal reference on the virtual 8-device mesh, and the training
step integration (forward + backward through ppermute)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from opsagent_tpu.ops.attention import causal_prefill_attention
from opsagent_tpu.parallel.mesh import make_mesh
from opsagent_tpu.parallel.ring import make_ring_attention


@pytest.mark.parametrize("dp,sp,tp", [(1, 4, 2), (2, 4, 1), (1, 8, 1)])
def test_ring_matches_dense_causal(dp, sp, tp):
    mesh = make_mesh(tp=tp, dp=dp, sp=sp)
    rng = np.random.default_rng(0)
    B, S, H, K, D = 2 * dp, 8 * sp, 4, 2, 16
    q = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, S, K, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, S, K, D)), jnp.float32)

    ref = causal_prefill_attention(q, k, v)
    ring = make_ring_attention(mesh)
    with mesh:
        got = jax.jit(ring)(q, k, v)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_ring_gradients_flow():
    """value_and_grad through the ring (ppermute in fori_loop) must compile
    and match dense-attention gradients."""
    mesh = make_mesh(tp=1, dp=1, sp=4, devices=jax.devices()[:4])
    rng = np.random.default_rng(1)
    B, S, H, K, D = 1, 16, 2, 2, 8
    q = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, S, K, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, S, K, D)), jnp.float32)
    ring = make_ring_attention(mesh)

    def loss_ring(q, k, v):
        return jnp.sum(ring(q, k, v) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(causal_prefill_attention(q, k, v) ** 2)

    with mesh:
        g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_dense):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4
        )


def test_train_step_with_ring_matches_dense():
    """Same data, same init: one training step with ring attention must give
    the same loss and gradient norm as the dense path."""
    from opsagent_tpu.models.config import get_config_preset
    from opsagent_tpu.training import TrainConfig, init_train_state, make_train_step

    cfg = get_config_preset("tiny-test")
    mesh = make_mesh(tp=2, dp=1, sp=4)
    rng = np.random.default_rng(2)
    tokens = jnp.asarray(rng.integers(1, 500, (2, 32)), jnp.int32)
    mask = jnp.ones((2, 32), jnp.float32)

    metrics = {}
    for ring in (False, True):
        tc = TrainConfig(remat=True, ring_attention=ring)
        params, opt_state = init_train_state(
            cfg, tc, mesh, jax.random.PRNGKey(0), dtype=jnp.float32
        )
        step = make_train_step(cfg, tc, mesh, dtype=jnp.float32)
        _, _, m = step(params, opt_state, tokens, mask)
        metrics[ring] = (float(m["loss"]), float(m["grad_norm"]))
    np.testing.assert_allclose(metrics[True][0], metrics[False][0], rtol=1e-5)
    np.testing.assert_allclose(metrics[True][1], metrics[False][1], rtol=1e-4)


def test_ring_ragged_lengths_match_dense():
    """VERDICT item 8: ragged right-padded batches on an sp mesh must match
    the dense oracle masked by per-sequence lengths (serving prefill)."""
    mesh = make_mesh(tp=1, dp=1, sp=4, devices=jax.devices()[:4])
    rng = np.random.default_rng(3)
    B, S, H, K, D = 3, 32, 4, 2, 16
    lengths = jnp.asarray([32, 17, 5], jnp.int32)
    q = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, S, K, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, S, K, D)), jnp.float32)

    ref = causal_prefill_attention(q, k, v, lengths=lengths)
    ring = make_ring_attention(mesh)
    with mesh:
        got = jax.jit(lambda q, k, v: ring(q, k, v, lengths=lengths))(q, k, v)
    # Compare only valid query positions; padded tails differ (ring defines
    # fully-masked rows as zeros, the dense ref as softmax over -inf).
    for b, n in enumerate([32, 17, 5]):
        np.testing.assert_allclose(
            np.asarray(got)[b, :n], np.asarray(ref)[b, :n],
            rtol=2e-5, atol=2e-5,
        )


def test_engine_prefill_with_sp_ring_matches_sp1():
    """Engine-level: serving prefill sharded over sp=2 (ring attention)
    must produce exactly the sp=1 engine's generations."""
    from opsagent_tpu.serving.engine import Engine, EngineConfig
    from opsagent_tpu.serving.sampler import SamplingParams

    kwargs = dict(
        model="tiny-test", dtype=jnp.float32, tp=1, page_size=4,
        num_pages=64, max_pages_per_seq=16, max_batch_size=2,
        prefill_buckets=(16, 32), prefix_cache=False,
    )
    prompts = [[257, 5, 6, 7, 8, 9, 10], [257, 40, 41]]
    e1 = Engine(EngineConfig(**kwargs))
    want = e1.generate(prompts, SamplingParams(max_tokens=6))

    e2 = Engine(EngineConfig(sp=2, **kwargs))
    assert e2.mesh.shape["sp"] == 2
    got = e2.generate(prompts, SamplingParams(max_tokens=6))
    assert got == want


@pytest.mark.slow
def test_engine_long_context_prefill_sp4_matches_sp1():
    """Config-4-scale shape at test size: a ~4k-token prompt prefilled
    through the sp=4 ragged ring must generate exactly what the sp=1
    engine does. This is the engine-level long-context evidence — the
    tiny parity test above covers the mechanism, this covers the SHAPE
    (multi-page prompt, large bucket, ring over a real 4-way split)."""
    from opsagent_tpu.serving.engine import Engine, EngineConfig
    from opsagent_tpu.serving.sampler import SamplingParams

    rng = np.random.default_rng(7)
    prompt = [257] + rng.integers(1, 500, size=4000).tolist()
    kwargs = dict(
        model="tiny-test", dtype=jnp.float32, tp=1, page_size=16,
        num_pages=600, max_pages_per_seq=300, max_batch_size=2,
        prefill_buckets=(1024, 4096), prefix_cache=False,
    )
    e1 = Engine(EngineConfig(**kwargs))
    want = e1.generate([prompt], SamplingParams(max_tokens=8))

    e2 = Engine(EngineConfig(sp=4, **kwargs))
    assert e2.mesh.shape["sp"] == 4
    got = e2.generate([prompt], SamplingParams(max_tokens=8))
    assert got == want
