"""Real-checkpoint end-to-end proof (VERDICT round-1 item 6): committed
fixtures pin loader -> model -> engine generate against golden outputs.

- tiny-llama-hf / tiny-qwen2-hf were written by the GENUINE HuggingFace
  implementations (transformers on CPU torch) along with their own forward
  logits and greedy continuations — an independent oracle: any drift in
  HF-name mapping, weight transposes, RoPE convention, norm epsilon, or
  bias handling makes these fail.
- tiny-deepseek-moe pins the DeepSeek MoE naming scheme (mlp.gate /
  mlp.experts.N / mlp.shared_experts) as a regression fixture (transformers
  has no in-tree DeepSeek-MoE to serve as an oracle).

Regenerate with ``python tests/fixtures/make_golden.py``.
"""

import os
from dataclasses import replace

import numpy as np
import jax.numpy as jnp
import pytest

from opsagent_tpu.models import llama
from opsagent_tpu.models.config import get_config_preset
from opsagent_tpu.models.loader import load_checkpoint
from opsagent_tpu.serving.engine import Engine, EngineConfig
from opsagent_tpu.serving.sampler import SamplingParams

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")

TINY = get_config_preset("tiny-test")
CASES = {
    "tiny-llama-hf": TINY,  # fixture mirrors the tiny-test architecture
    "tiny-qwen2-hf": replace(TINY, attn_bias=True, rms_norm_eps=1e-6),
    # Qwen3: per-head q/k RMSNorm + explicit head_dim != hidden/heads.
    "tiny-qwen3-hf": replace(
        TINY, qk_norm=True, head_dim=32, rms_norm_eps=1e-6
    ),
    # "auto" = derive the config from the fixture's own config.json via
    # config_from_hf — the golden run then pins the WHOLE auto path
    # (derivation + loader + forward) against the HF oracle.
    "tiny-qwen3-moe-hf": "auto",
    "tiny-deepseek-moe": get_config_preset("tiny-moe"),
}


def _case_cfg(name, path):
    cfg = CASES[name]
    if cfg == "auto":
        from opsagent_tpu.models.config import config_from_hf

        cfg = config_from_hf(path)
    return cfg


def _fixture(name):
    path = os.path.join(FIXTURES, name)
    if not os.path.isdir(path):
        pytest.skip(f"fixture {name} not generated")
    golden = np.load(os.path.join(path, "golden.npz"))
    return path, golden


@pytest.mark.parametrize("name", sorted(CASES))
def test_loader_forward_matches_golden_logits(name):
    path, golden = _fixture(name)
    cfg = _case_cfg(name, path)
    params = load_checkpoint(path, cfg, dtype=jnp.float32)
    prompt = golden["prompt"].tolist()
    logits = llama.forward_full(
        params, cfg, jnp.asarray([prompt], jnp.int32), dtype=jnp.float32
    )
    np.testing.assert_allclose(
        np.asarray(logits[0, -1]), golden["last_logits"],
        rtol=2e-4, atol=2e-4,
    )


@pytest.mark.parametrize("name", sorted(CASES))
def test_engine_generate_matches_golden_greedy(name):
    """End to end through the serving stack: checkpoint dir -> loader ->
    prefill -> paged block decode must reproduce the golden greedy
    continuation token for token."""
    path, golden = _fixture(name)
    cfg = _case_cfg(name, path)
    eng = Engine(
        EngineConfig(
            model="unused", checkpoint=path, dtype=jnp.float32, tp=1,
            page_size=4, num_pages=64, max_pages_per_seq=16,
            max_batch_size=2, prefill_buckets=(16, 32),
        ),
        model_cfg=cfg,
    )
    prompt = golden["prompt"].tolist()
    want = golden["greedy"].tolist()
    got = eng.generate([prompt], SamplingParams(max_tokens=len(want)))[0]
    assert got == want
