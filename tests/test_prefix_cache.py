"""Prefix caching: allocator trie semantics, tail-prefill numerical parity,
and end-to-end reuse across engine requests (the O(n²)→O(n) fix for the
ReAct loop's resend-everything pattern, SURVEY.md §5/§7)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from opsagent_tpu.serving.kvcache import OutOfPages, PageAllocator


P = 4  # page size for allocator tests


def toks(n, base=0):
    return [base + i for i in range(n)]


class TestAllocatorTrie:
    def test_roundtrip_match_after_free(self):
        a = PageAllocator(num_pages=16, page_size=P, max_pages_per_seq=8)
        sid = a.allocate(10)  # 3 pages, 2 full
        assert a.match_prefix(toks(10)) == []
        a.free(sid, tokens=toks(10))
        pages = a.match_prefix(toks(10))
        assert len(pages) == 2  # only full pages cached
        # Shorter and longer prompts match the right amount.
        assert len(a.match_prefix(toks(4))) == 1
        assert len(a.match_prefix(toks(3))) == 0
        assert len(a.match_prefix(toks(30))) == 2
        # Different content: no match.
        assert a.match_prefix(toks(10, base=100)) == []

    def test_shared_allocation_and_refcount(self):
        a = PageAllocator(num_pages=8, page_size=P, max_pages_per_seq=8)
        s1 = a.allocate(8)
        a.free(s1, tokens=toks(8))          # 2 cached pages
        prefix = a.match_prefix(toks(8))
        s2 = a.allocate(9, prefix_pages=prefix)
        # 2 shared + 1 fresh page.
        assert a._seqs[s2].num_shared == 2
        assert a.hit_tokens == 8
        # Shared pages are pinned: exhaust the pool (2 shared + 1 fresh used,
        # 5 free), eviction must not touch the refcounted pages.
        s3 = a.allocate(20)  # 5 pages
        with pytest.raises(OutOfPages):
            a.allocate(4)
        a.free(s3)
        a.free(s2, tokens=toks(9))

    def test_eviction_lru_leaves_first(self):
        a = PageAllocator(num_pages=4, page_size=P, max_pages_per_seq=4)
        s1 = a.allocate(8)
        a.free(s1, tokens=toks(8))           # cache chain: pg A <- pg B
        s2 = a.allocate(8, prefix_pages=a.match_prefix(toks(8)))
        a.free(s2, tokens=toks(8))           # still 2 cached, 2 free
        # Allocating 3 pages forces one eviction: the LEAF (second page)
        # must go before its parent.
        s3 = a.allocate(12, prefix_pages=[])
        assert len(a.match_prefix(toks(8))) == 1   # parent survived
        a.free(s3)

    def test_disabled_cache_frees_everything(self):
        a = PageAllocator(8, P, 8, prefix_cache=False)
        sid = a.allocate(8)
        a.free(sid, tokens=toks(8))
        assert a.match_prefix(toks(8)) == []
        assert len(a._free) == 8


class TestTailPrefillParity:
    def test_prefill_with_prefix_matches_full_prefill(self):
        from opsagent_tpu.models import llama
        from opsagent_tpu.models.config import get_config_preset

        cfg = get_config_preset("tiny-test")
        params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
        PS, NP, MaxP = 8, 16, 6
        rng = np.random.default_rng(3)
        n = 29               # 3 full pages (24) + 5-token tail
        prompt = rng.integers(1, cfg.vocab_size, n).astype(np.int32)

        # Path A: one full prefill.
        cache_a = llama.make_cache(cfg, NP, PS, dtype=jnp.float32)
        table_a = np.full((1, MaxP), -1, np.int32)
        table_a[0, :4] = [0, 1, 2, 3]
        S = 32
        tok_a = np.zeros((1, S), np.int32)
        tok_a[0, :n] = prompt
        logits_a, cache_a = llama.prefill(
            params, cfg, jnp.asarray(tok_a), jnp.asarray([n], jnp.int32),
            cache_a, jnp.asarray(table_a), dtype=jnp.float32,
        )

        # Path B: prefill the 24-token prefix, then tail via
        # prefill_with_prefix into the same pages.
        cache_b = llama.make_cache(cfg, NP, PS, dtype=jnp.float32)
        table_b = np.full((1, MaxP), -1, np.int32)
        table_b[0, :4] = [5, 6, 7, 8]
        tok_p = np.zeros((1, 24), np.int32)
        tok_p[0, :] = prompt[:24]
        _, cache_b = llama.prefill(
            params, cfg, jnp.asarray(tok_p), jnp.asarray([24], jnp.int32),
            cache_b, jnp.asarray(table_b), dtype=jnp.float32,
        )
        tok_t = np.zeros((1, 8), np.int32)
        tok_t[0, :5] = prompt[24:]
        logits_b, cache_b = llama.prefill_with_prefix(
            params, cfg, jnp.asarray(tok_t),
            jnp.asarray([24], jnp.int32), jnp.asarray([5], jnp.int32),
            cache_b, jnp.asarray(table_b), dtype=jnp.float32,
        )
        np.testing.assert_allclose(
            np.asarray(logits_a), np.asarray(logits_b), rtol=2e-4, atol=2e-4
        )
        # KV written by the tail matches the full-prefill KV (same tokens,
        # same positions, different pages).
        ka = np.asarray(cache_a["k"])[:, table_a[0, 3]]
        kb = np.asarray(cache_b["k"])[:, table_b[0, 3]]
        np.testing.assert_allclose(ka[:, :5], kb[:, :5], rtol=2e-4, atol=2e-4)


class TestEnginePrefixReuse:
    @pytest.fixture()
    def engine(self):
        from opsagent_tpu.serving.engine import Engine, EngineConfig

        return Engine(EngineConfig(
            model="tiny-test", dtype=jnp.float32, page_size=8, num_pages=64,
            max_pages_per_seq=16, max_batch_size=2,
            prefill_buckets=(16, 32, 64), max_new_tokens_default=8,
        ))

    def test_repeat_prompt_hits_cache_and_matches(self, engine):
        from opsagent_tpu.serving.sampler import SamplingParams

        rng = np.random.default_rng(0)
        prompt = rng.integers(1, engine.model_cfg.vocab_size, 30).tolist()
        sp = SamplingParams(temperature=0.0, max_tokens=6)
        out1 = engine.generate([prompt], sp)[0]
        assert engine.alloc.hit_tokens == 0
        out2 = engine.generate([prompt], sp)[0]
        assert engine.alloc.hit_tokens >= 24  # ≥3 pages of 8 reused
        assert out1 == out2                  # greedy determinism across reuse

    def test_growing_history_reuses_previous_turns(self, engine):
        """The ReAct pattern: each request = previous history + new text."""
        from opsagent_tpu.serving.sampler import SamplingParams

        rng = np.random.default_rng(1)
        sp = SamplingParams(temperature=0.0, max_tokens=4)
        history = rng.integers(1, 200, 24).tolist()
        engine.generate([history], sp)
        before = engine.alloc.hit_tokens
        history2 = history + rng.integers(1, 200, 24).tolist()
        engine.generate([history2], sp)
        assert engine.alloc.hit_tokens - before >= 16
        before = engine.alloc.hit_tokens
        history3 = history2 + rng.integers(1, 200, 24).tolist()
        engine.generate([history3], sp)
        assert engine.alloc.hit_tokens - before >= 40

    def test_cache_off_still_correct(self):
        from opsagent_tpu.serving.engine import Engine, EngineConfig
        from opsagent_tpu.serving.sampler import SamplingParams

        eng = Engine(EngineConfig(
            model="tiny-test", dtype=jnp.float32, page_size=8, num_pages=64,
            max_pages_per_seq=16, max_batch_size=2,
            prefill_buckets=(16, 32, 64), prefix_cache=False,
        ))
        rng = np.random.default_rng(0)
        prompt = rng.integers(1, eng.model_cfg.vocab_size, 30).tolist()
        sp = SamplingParams(temperature=0.0, max_tokens=6)
        out1 = eng.generate([prompt], sp)[0]
        out2 = eng.generate([prompt], sp)[0]
        assert out1 == out2
        assert eng.alloc.hit_tokens == 0

    def test_chunked_prefill_beyond_largest_bucket(self, engine):
        """A cold prompt longer than the largest prefill bucket (64) chunks
        through it and must produce the same continuation as the same prompt
        admitted fully-cached — admission no longer depends on cache state."""
        from opsagent_tpu.serving.sampler import SamplingParams

        rng = np.random.default_rng(5)
        sp = SamplingParams(temperature=0.0, max_tokens=4)
        prompt = rng.integers(1, 200, 100).tolist()  # > largest bucket 64
        out_cold = engine.generate([prompt], sp)[0]
        out_warm = engine.generate([prompt], sp)[0]  # now prefix-cached
        assert out_cold == out_warm

    def test_pressure_eviction_keeps_generating(self, engine):
        """Fill the pool with cached pages, then admit requests that force
        evictions; generation must stay correct (no page leaks/corruption)."""
        from opsagent_tpu.serving.sampler import SamplingParams

        rng = np.random.default_rng(2)
        sp = SamplingParams(temperature=0.0, max_tokens=4)
        outs = {}
        for i in range(12):
            prompt = rng.integers(1, 200, 40).tolist()
            outs[i] = (prompt, engine.generate([prompt], sp)[0])
        # Re-run an early prompt (its pages may have been evicted): result
        # must be identical either way.
        prompt, expected = outs[0]
        assert engine.generate([prompt], sp)[0] == expected
