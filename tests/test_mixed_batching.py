"""Mixed prefill+decode batching (one weight stream per step).

Covers the ISSUE-2 acceptance gates on the tiny CPU engine: (a) greedy
token equivalence of the mixed path vs. the split prefill/decode path,
(b) the scheduler's token-budget policy (decode lanes funded first,
remainder to the oldest admitting prompts, honoring max_step_tokens),
(c) ZERO post-warmup XLA compiles across varied mixed-batch compositions
(the r04 sessions invariant, extended to the mixed programs), and
(d) prefix-cache hits still applying to chunks seated in mixed
dispatches.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from opsagent_tpu.serving.engine import Engine, EngineConfig
from opsagent_tpu.serving.sampler import SamplingParams
from opsagent_tpu.serving.scheduler import Request, Scheduler

BASE = dict(
    model="tiny-test", dtype=jnp.float32, tp=1, page_size=4,
    num_pages=128, max_pages_per_seq=24, max_batch_size=4,
    prefill_buckets=(8, 16), decode_block=4,
    mixed_buckets=(4, 8, 16), max_step_tokens=32,
    # This file tests the SYNCHRONOUS mixed tick's contract (ISSUE-2);
    # the one-step-lookahead pipeline has its own acceptance suite in
    # tests/test_async_runtime.py.
    async_depth=1,
)

# Count real XLA compiles process-wide: the monitoring event fires once
# per backend compile and never on jit-cache hits. Registered once at
# import (jax.monitoring has no public deregistration); tests diff the
# counter around the window they care about.
_COMPILES: list[str] = []


def _on_event(name: str, *a, **kw) -> None:
    if name == "/jax/core/compile/backend_compile_duration":
        _COMPILES.append(name)


jax.monitoring.register_event_duration_secs_listener(_on_event)


def _drain_all(eng, sids):
    live = [s for s in sids if not eng.sequences[s].done]
    while live:
        eng.step_block(sorted(live))
        live = [s for s in live if not eng.sequences[s].done]
    eng.drain()


def test_mixed_scheduler_matches_split_greedy():
    """(a) End-to-end through the scheduler: concurrent short + long
    prompts decoded under the mixed tick must be token-identical to the
    split-path oracle."""
    prompts = [
        [257, 9, 8, 7],
        [257] + list(range(1, 40)),     # multiple chunks
        [257, 5, 5, 5, 5, 5],
    ]
    budgets = [12, 6, 9]
    split = Engine(EngineConfig(mixed_batching=False, **BASE))
    want = [
        split.generate([p], SamplingParams(max_tokens=n))[0]
        for p, n in zip(prompts, budgets)
    ]

    eng = Engine(EngineConfig(mixed_batching=True, **BASE))
    sched = Scheduler(eng)
    sched.start()
    try:
        reqs = [
            sched.submit(Request(p, SamplingParams(max_tokens=n)))
            for p, n in zip(prompts, budgets)
        ]
        for r in reqs:
            assert r.done.wait(180)
            assert not r.error, r.error
        assert [r.tokens for r in reqs] == want
    finally:
        sched.stop()


def test_step_mixed_direct_matches_split_greedy():
    """(a) Engine-level: driving admission chunk-by-chunk through
    step_mixed while a decode lane rides along must reproduce both
    sequences' split-path generations exactly."""
    short = [257, 9, 8, 7]
    long_prompt = [257] + list(range(1, 40))
    split = Engine(EngineConfig(mixed_batching=False, **BASE))
    want_short = split.generate([short], SamplingParams(max_tokens=12))[0]
    want_long = split.generate([long_prompt], SamplingParams(max_tokens=6))[0]

    eng = Engine(EngineConfig(mixed_batching=True, **BASE))
    a = eng.add_request(short, SamplingParams(max_tokens=12))
    b = eng.begin_request(long_prompt, SamplingParams(max_tokens=6))
    collected = list(eng.sequences[a].tokens)
    mixed_dispatches = 0
    while b in eng._prefilling:
        done, total = eng.prefill_progress(b)
        dids = [a] if not eng.sequences[a].done else []
        d_out, p_out = eng.step_mixed(dids, {b: min(total - done, 16)})
        mixed_dispatches += 1
        collected.extend(d_out.get(a, []))
        assert not isinstance(p_out[b], Exception)
    assert mixed_dispatches >= 3          # 40 tokens through bucket-16 chunks
    _drain_all(eng, [a, b])
    collected.extend([])  # decode lane tokens already folded in
    while not eng.sequences[a].done:
        collected.extend(eng.step_block([a]).get(a, []))
    got_a, got_b = eng.finish(a), eng.finish(b)
    assert got_a == want_short
    assert got_b == want_long
    # The decode lane advanced DURING admission (mixed piggybacking).
    assert len(collected) > 1


def test_budget_policy_honors_max_step_tokens_and_decode_priority():
    """(b) Decode lanes are funded first; the admitting prompt gets
    exactly max_step_tokens - lanes (capped by the bucket ceiling), and a
    budget fully consumed by decode lanes yields no mixed dispatch."""
    cfg = dict(BASE, max_step_tokens=6, mixed_buckets=(4, 8, 16))
    eng = Engine(EngineConfig(**cfg))
    sched = Scheduler(eng)  # never started: ticks driven by hand
    short = [257, 1, 2, 3]
    long_prompt = [257] + list(range(1, 30))
    sched.submit(Request(short, SamplingParams(max_tokens=8)))
    sched._drain_queue()
    sched._try_admit()
    # Finish the short prompt's admission so it becomes a decode lane.
    while sched._prefilling:
        sched._advance_prefill()
    assert len(sched._running) == 1
    sched.submit(Request(long_prompt, SamplingParams(max_tokens=4)))
    sched._drain_queue()
    sched._try_admit()
    (bid,) = list(sched._prefilling)
    assert sched._mixed_tick() is True
    done, total = eng.prefill_progress(bid)
    # budget 6 - 1 decode lane = 5 chunk tokens, NOT the full bucket.
    assert done == 5
    # Starve the prefill budget entirely: lanes >= max_step_tokens.
    eng.cfg.max_step_tokens = 1
    assert sched._mixed_tick() is False   # falls back to the split tick
    eng.cfg.max_step_tokens = 64
    assert sched._mixed_tick() is True
    done2, _ = eng.prefill_progress(bid)
    assert done2 - done == min(16, total - done)  # bucket-capped chunk
    # Drain cleanly so the engine holds no half-admitted state.
    while sched._prefilling:
        if not sched._mixed_tick():
            sched._advance_prefill()
        sched._reap()
    for sid in list(sched._running):
        while not eng.sequences[sid].done:
            eng.step_block([sid])
        eng.drain()
    sched._reap()


def test_zero_compiles_after_warmup_across_mixed_compositions():
    """(c) The r04 invariant extended to mixed batching: after a
    sessions-level warmup, NO mixed-batch composition — varying decode
    lane counts, chunk sizes across every bucket, completing prompts,
    prefix-cache-backed chunks — may trigger an XLA compile."""
    cfg = EngineConfig(mixed_batching=True, **BASE)
    eng = Engine(cfg)
    eng.warmup("sessions")
    sampling = SamplingParams(max_tokens=6)

    n0 = len(_COMPILES)
    rng = np.random.default_rng(3)
    # Composition sweep: prompts sized to hit chunk buckets 4/8/16 with
    # 0..2 decode lanes riding along.
    sids: list[int] = []
    for plen in (3, 7, 13, 21, 37):
        prompt = [257] + [int(t) for t in rng.integers(1, 400, plen - 1)]
        b = eng.begin_request(prompt, sampling)
        while b in eng._prefilling:
            done, total = eng.prefill_progress(b)
            lanes = [s for s in sids if not eng.sequences[s].done][:2]
            eng.step_mixed(lanes, {b: min(total - done, 16)})
        sids.append(b)
    _drain_all(eng, sids)
    for s in sids:
        eng.finish(s)
    assert len(_COMPILES) == n0, (
        f"{len(_COMPILES) - n0} post-warmup compiles in mixed dispatches"
    )


def test_prefix_cache_hits_apply_to_mixed_chunks():
    """(d) A prompt sharing a cached prefix must start its mixed-path
    admission AT the matched offset (skipping the cached pages) and still
    generate exactly the uncached oracle's tokens."""
    base = [257] + list(range(1, 25))          # 24 tokens -> 6 full pages
    extended = base + [300, 301, 302, 303]
    split = Engine(EngineConfig(mixed_batching=False, **BASE))
    want = split.generate([extended], SamplingParams(max_tokens=6))[0]

    eng = Engine(EngineConfig(mixed_batching=True, **BASE))
    # Populate the trie: run the base prompt to completion and free it.
    a = eng.add_request(base, SamplingParams(max_tokens=4))
    _drain_all(eng, [a])
    eng.finish(a)

    hit0 = eng.alloc.hit_tokens
    b = eng.begin_request(extended, SamplingParams(max_tokens=6))
    assert eng.alloc.hit_tokens > hit0         # prefix matched at admission
    matched = eng._prefilling[b]
    assert matched > 0 and matched % eng.cfg.page_size == 0
    chunks = 0
    while b in eng._prefilling:
        done, total = eng.prefill_progress(b)
        assert done >= matched                 # never re-prefills the prefix
        eng.step_mixed([], {b: min(total - done, 16)})
        chunks += 1
    # The un-matched tail is < one bucket: exactly one mixed chunk.
    assert chunks == 1
    _drain_all(eng, [b])
    assert eng.finish(b) == want


def test_hosted_rows_fall_back_to_split_path():
    """A request needing host-side per-token work (logprobs) must route
    the tick to the split path — and still complete correctly alongside
    an admitting prompt under the mixed scheduler."""
    eng = Engine(EngineConfig(mixed_batching=True, **BASE))
    split = Engine(EngineConfig(mixed_batching=False, **BASE))
    p1 = [257, 3, 1, 4, 1, 5]
    p2 = [257] + list(range(1, 20))
    want1 = split.generate([p1], SamplingParams(max_tokens=5))[0]
    want2 = split.generate([p2], SamplingParams(max_tokens=5))[0]

    sched = Scheduler(eng)
    sched.start()
    try:
        r1 = sched.submit(Request(
            p1, SamplingParams(max_tokens=5, logprobs=True, top_logprobs=2)
        ))
        r2 = sched.submit(Request(p2, SamplingParams(max_tokens=5)))
        assert r1.done.wait(180) and r2.done.wait(180)
        assert not r1.error and not r2.error
        assert r1.tokens == want1
        assert r2.tokens == want2
        assert len(r1.logprob_data) == len(r1.tokens)
    finally:
        sched.stop()


def test_mixed_dispatch_composition_metrics_recorded():
    """The obs composition series (decode lanes, prefill tokens, budget
    utilization) must tick once per mixed dispatch."""
    from opsagent_tpu import obs

    snap0 = obs.metrics_snapshot()
    c0 = snap0.get("opsagent_mixed_dispatch_decode_lanes_count", 0)
    eng = Engine(EngineConfig(mixed_batching=True, **BASE))
    a = eng.add_request([257, 2, 3, 4], SamplingParams(max_tokens=8))
    b = eng.begin_request(
        [257] + list(range(1, 20)), SamplingParams(max_tokens=4)
    )
    n = 0
    while b in eng._prefilling:
        done, total = eng.prefill_progress(b)
        eng.step_mixed([a], {b: min(total - done, 16)})
        n += 1
    snap1 = obs.metrics_snapshot()
    assert snap1["opsagent_mixed_dispatch_decode_lanes_count"] == c0 + n
    assert snap1["opsagent_mixed_dispatch_prefill_tokens_sum"] >= 19 - 16
    assert (
        snap1['opsagent_decode_dispatches_total{kind="mixed"}']
        >= snap0.get('opsagent_decode_dispatches_total{kind="mixed"}', 0) + n
    )
    _drain_all(eng, [a, b])
    eng.finish(a), eng.finish(b)


def test_mixed_backends_byte_identical_with_int8_kv(monkeypatch):
    """step_mixed with kv_quantize="int8" across attention backends
    (xla gather vs the ragged manual-DMA kernel, interpret off-chip):
    chunked admission + interleaved decode lanes must produce
    byte-identical greedy output, the resolved impl must be the
    requested backend (the old QuantizedPages fallback forced xla), and
    no mixed composition may compile post-warmup."""
    prompts = [
        [257] + list(range(1, 12)),
        [257] + [5, 9, 2, 8, 1, 7, 3, 3, 4, 6, 2, 9, 8, 1, 5, 5, 2],
        [257, 4, 4, 2],
    ]
    monkeypatch.setenv("OPSAGENT_PALLAS_INTERPRET", "1")
    outs = {}
    for backend in ("xla", "pallas-dma"):
        monkeypatch.setenv("OPSAGENT_PAGED_BACKEND", backend)
        cfg = EngineConfig(
            mixed_batching=True, kv_quantize="int8", **BASE
        )
        eng = Engine(cfg)
        assert eng.attn_impl == backend
        eng.warmup("sessions")
        sampling = SamplingParams(max_tokens=8)
        n0 = len(_COMPILES)
        sids: list[int] = []
        for prompt in prompts:
            b = eng.begin_request(prompt, sampling)
            while b in eng._prefilling:
                done, total = eng.prefill_progress(b)
                lanes = [s for s in sids if not eng.sequences[s].done][:2]
                eng.step_mixed(lanes, {b: min(total - done, 16)})
            sids.append(b)
        live = [s for s in sids if not eng.sequences[s].done]
        while live:
            eng.step_mixed(live, {})
            live = [s for s in live if not eng.sequences[s].done]
        outs[backend] = [eng.finish(s) for s in sids]
        assert len(_COMPILES) == n0, (
            f"{len(_COMPILES) - n0} post-warmup compiles on {backend}"
        )
    assert outs["xla"] == outs["pallas-dma"], outs
