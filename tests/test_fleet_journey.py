"""Fleet-scope request journeys (router ID propagation + stitched
cross-replica timelines + fleet flight ledger).

The acceptance gate (ISSUE 16): a streamed request that crosses replicas
through a forced mid-SSE failover AND a pagestore peer fault-in yields
ONE stitched timeline from the router — segments from at least two
replicas plus the router-side failover and fault-in windows, >= 95% of
the journey wall-time covered by attributed segments, and a monotonic,
non-overlapping segment ordering after clock-skew correction — with
byte-identical client output and zero post-warmup compiles.
"""

import time

import jax.numpy as jnp

from opsagent_tpu import obs
from opsagent_tpu.obs import timeline as obs_timeline
from opsagent_tpu.serving import faults
from opsagent_tpu.serving.api import ServingStack
from opsagent_tpu.serving.engine import Engine, EngineConfig
from opsagent_tpu.serving.fleet.registry import (
    ClockSync,
    ReplicaInfo,
    ReplicaRegistry,
)
from opsagent_tpu.serving.fleet.router import FleetRouter

BASE = dict(
    model="tiny-test", dtype=jnp.float32, tp=1, page_size=4,
    num_pages=256, max_pages_per_seq=64, max_batch_size=4,
    prefill_buckets=(16, 32, 64), decode_block=4, seed=0,
    offload=True,
)


def _fleet(n=2, **router_kw):
    router = FleetRouter(**router_kw)
    stacks = []
    for i in range(n):
        stack = ServingStack(Engine(EngineConfig(**BASE)))
        stacks.append(stack)
        router.add_local(stack, f"r{i}")
    return router, stacks


def _close(stacks):
    for s in stacks:
        s.close()


# -- S2: heartbeat clock sync -------------------------------------------------
class TestClockSync:
    def test_ewma_first_sample_snaps_then_smooths(self):
        c = ClockSync()
        c.update(0.5, 0.02)
        assert c.offset_s == 0.5 and c.rtt_s == 0.02 and c.samples == 1
        c.update(1.5, 0.04)
        # EWMA alpha=0.3: 0.5 + 0.3 * (1.5 - 0.5) = 0.8
        assert abs(c.offset_s - 0.8) < 1e-9
        assert abs(c.rtt_s - 0.026) < 1e-9

    def test_heartbeat_echo_estimates_synthetic_skew(self):
        """A replica whose wall clock runs 42s ahead of the router's:
        the echo protocol recovers the offset to within the RTT."""
        skew = 42.0
        reg = ReplicaRegistry()
        reg.register(ReplicaInfo(replica_id="remote", url="http://x"))
        # The replica echoes a router_ts it received `held` seconds ago
        # (on its own monotonic clock); its wall clock reads router+skew.
        held = 0.05
        ok = reg.heartbeat(
            "remote",
            replica_ts=time.time() + skew,
            echo_router_ts=time.time() - held,
            echo_held_s=held,
        )
        assert ok
        c = reg.clock_of("remote")
        assert c is not None and c.samples == 1
        assert abs(c.offset_s - skew) < 0.05
        assert 0.0 <= c.rtt_s < 0.05
        assert abs(reg.clock_offsets()["remote"] - skew) < 0.05
        # The estimate reaches the metrics surface and health snapshot.
        assert abs(
            obs.metrics_snapshot().get(
                'opsagent_fleet_clock_skew_seconds{replica="remote"}', 0.0
            ) - skew
        ) < 0.05
        snap = reg.health_snapshot(clock=True)
        assert abs(snap["remote"]["clock_offset_s"] - skew) < 0.05
        assert snap["remote"]["clock_samples"] == 1
        # Default (clock=False) keeps the legacy {rid: state} shape.
        assert reg.health_snapshot()["remote"] in (
            "healthy", "suspect", "ejected", "half-open"
        )

    def test_local_replicas_are_seeded_at_zero_offset(self):
        reg = ReplicaRegistry()
        reg.register(ReplicaInfo(replica_id="loc", local=True))
        c = reg.clock_of("loc")
        assert c is not None and c.samples >= 1
        assert c.offset_s == 0.0
        # Echo fields on a local replica never move the estimate.
        reg.heartbeat(
            "loc", replica_ts=time.time() + 99,
            echo_router_ts=time.time(), echo_held_s=0.0,
        )
        assert reg.clock_of("loc").offset_s == 0.0

    def test_deregister_drops_clock_state(self):
        reg = ReplicaRegistry()
        reg.register(ReplicaInfo(replica_id="gone", url="http://x"))
        assert reg.clock_of("gone") is not None
        reg.deregister("gone")
        assert reg.clock_of("gone") is None
        assert "gone" not in reg.clock_offsets()


# -- S1: participants map -----------------------------------------------------
class TestParticipantsMap:
    def test_journey_records_every_hop_and_replica(self):
        router = FleetRouter()
        jid = router._new_journey()
        assert jid and jid.startswith("chatcmpl-")
        router._note_hop(jid, "r0", "stream", failovers=0)
        router._note_hop(jid, "r1", "failover", failovers=1)
        router._note_shape(jid, "failover")
        rec = router.participants_of(jid)
        assert rec["replicas"] == ["r0", "r1"]
        assert [h["hop"] for h in rec["hops"]] == ["stream", "failover"]
        assert rec["shape"] == "failover"
        assert all(h["wall"] > 0 for h in rec["hops"])
        assert router.owner_of(jid) == "r1"

    def test_shape_escalates_but_never_downgrades(self):
        router = FleetRouter()
        jid = router._new_journey()
        router._note_shape(jid, "failover")
        router._note_shape(jid, "retried")
        assert router.participants_of(jid)["shape"] == "failover"

    def test_map_is_bounded_lru(self):
        router = FleetRouter()
        router._max_map = 8
        jids = [router._new_journey() for _ in range(12)]
        assert router.participants_of(jids[0]) is None
        assert router.participants_of(jids[-1]) is not None
        with router._lock:
            assert len(router._participants) == 8

    def test_journeys_off_mints_nothing(self):
        router = FleetRouter(journeys=False)
        assert router._new_journey() is None
        router._note_hop(None, "r0", "route")      # all no-ops
        router._note_shape(None, "failover")
        router._finish_journey(None)
        with router._lock:
            assert not router._participants

    def test_finish_counts_shape_exactly_once(self):
        router = FleetRouter()
        key = {"shape": "hedged", "class": "interactive"}
        before = obs.FLEET_JOURNEYS.value(**key)
        jid = router._new_journey()
        router._note_shape(jid, "hedged")
        router._finish_journey(jid)
        router._finish_journey(jid)
        assert obs.FLEET_JOURNEYS.value(**key) == before + 1


# -- stitcher unit behavior ---------------------------------------------------
def _mk_source(t0_wall, phases, legs=None):
    return {
        "request_id": "chatcmpl-x", "t0_wall": t0_wall,
        "fleet_legs": legs or [],
        "duration_ms": max(p[2] for p in phases),
        "phases": [
            {"phase": p[0], "start_ms": p[1], "end_ms": p[2],
             "duration_ms": p[2] - p[1]}
            for p in phases
        ],
        "goodput": {}, "events": [],
    }


class TestStitchFleet:
    def test_skew_correction_orders_remote_segments(self):
        """Replica B's clock runs 10s ahead; without correction its
        segments would land far in the future. With offsets they
        interleave correctly after A's."""
        t0 = 1000.0
        src_a = _mk_source(t0, [("prefill", 0.0, 40.0),
                                ("decode", 40.0, 100.0)])
        src_b = _mk_source(
            t0 + 10.0 + 0.1, [("decode", 0.0, 80.0)]
        )   # B dispatched 100ms after A, but B's wall is +10s
        out = obs_timeline.stitch_fleet(
            "chatcmpl-x", {"ra": src_a, "rb": src_b},
            journey={"t0_wall": t0, "shape": "failover",
                     "replicas": ["ra", "rb"], "hops": []},
            offsets={"ra": 0.0, "rb": 10.0},
        )
        assert out["fleet"] is True
        assert out["replicas"] == ["ra", "rb"]
        assert out["duration_ms"] < 1000.0   # the 10s skew is gone
        segs = out["segments"]
        # Monotonic and non-overlapping after correction.
        for prev, cur in zip(segs, segs[1:]):
            assert cur["start_ms"] >= prev["end_ms"] - 1e-6
        lanes = {s["replica"] for s in segs}
        assert lanes == {"ra", "rb"}
        assert out["clock_offset_ms"]["rb"] == 10000.0

    def test_shared_source_splits_lanes_by_fleet_legs(self):
        t0 = 2000.0
        shared = _mk_source(
            t0,
            [("prefill", 0.0, 30.0), ("decode", 30.0, 60.0),
             ("decode", 70.0, 120.0)],
            legs=[
                {"replica": "r0", "hop": "stream",
                 "start_ms": 0.0, "end_ms": 120.0},
                {"replica": "r1", "hop": "failover",
                 "start_ms": 65.0, "end_ms": 120.0},
            ],
        )
        out = obs_timeline.stitch_fleet(
            "chatcmpl-x", {"_shared": shared},
            journey={"t0_wall": t0, "shape": "failover",
                     "replicas": ["r0", "r1"], "hops": []},
        )
        by_lane = {
            r: [s["phase"] for s in segs]
            for r, segs in out["lanes"].items()
        }
        # The innermost (failover) leg claims the late decode segment.
        assert by_lane["r0"] == ["prefill", "decode"]
        assert by_lane["r1"] == ["decode"]

    def test_windows_from_flight_events_and_reaped_degrade(self):
        t0 = 3000.0
        src = _mk_source(t0, [("decode", 0.0, 50.0)])
        out = obs_timeline.stitch_fleet(
            "chatcmpl-x", {"r0": src},
            journey={
                "t0_wall": t0 - 0.01, "shape": "failover",
                "replicas": ["r0", "r1"],
                "hops": [
                    {"hop": "stream", "replica": "r0", "wall": t0},
                    {"hop": "failover", "replica": "r1",
                     "wall": t0 + 0.2},
                ],
            },
            reaped=["r1"],
            events=[
                {"kind": "failover", "wall": t0 + 0.15, "replica": "r0"},
                {"kind": "page_fault_in", "phase": "enter",
                 "wall": t0 + 0.21, "replica": "r1"},
                {"kind": "page_fault_in", "phase": "exit",
                 "wall": t0 + 0.25, "replica": "r1", "pages": 3},
            ],
        )
        kinds = {w["kind"] for w in out["windows"]}
        assert {"routing", "failover", "fault_in"} <= kinds
        fo = next(w for w in out["windows"] if w["kind"] == "failover")
        # The failover window runs to the next hop dispatch.
        assert abs(fo["duration_ms"] - 50.0) < 1.0
        fi = next(w for w in out["windows"] if w["kind"] == "fault_in")
        assert fi["pages"] == 3
        assert out["reaped"] == ["r1"]
        text = obs_timeline.render_fleet_gantt(out)
        assert "degraded" in text and "r1" in text
        assert "fault_in" in text

    def test_empty_sources_return_zeroed_shell(self):
        out = obs_timeline.stitch_fleet("chatcmpl-x", {})
        assert out["fleet"] is True and out["segments"] == []
        assert out["coverage"] == 0.0


# -- ID propagation: the engine adopts the router's journey id ----------------
class TestIdAdoption:
    def test_response_id_is_the_journey_id(self):
        router, stacks = _fleet(1)
        try:
            resp = router.complete({
                "messages": [{"role": "user", "content": "adopt me"}],
                "max_tokens": 4, "temperature": 0,
            })
            rid = resp["id"]
            rec = router.participants_of(rid)
            assert rec is not None, "response id must BE the journey id"
            assert rec["replicas"] == ["r0"]
            assert rec["hops"][0]["hop"] == "route"
            # The engine-side trace exists under the same id.
            assert obs.timeline.assemble(rid) is not None
        finally:
            _close(stacks)

    def test_journeys_off_keeps_engine_minted_ids(self):
        router, stacks = _fleet(1, journeys=False)
        try:
            resp = router.complete({
                "messages": [{"role": "user", "content": "no stamps"}],
                "max_tokens": 4, "temperature": 0,
            })
            rid = resp["id"]
            # No journey record beyond the minimal owner entry.
            assert router.owner_of(rid) == "r0"
            rec = router.participants_of(rid)
            assert rec["hops"] == []
        finally:
            _close(stacks)

    def test_hop_header_synthesis_on_the_engine_server(self):
        """HTTP replicas receive the hop as X-Fleet-* headers when the
        body lost the field (proxies that re-serialize): the engine
        server synthesizes body['fleet_hop'] from them."""
        from aiohttp.test_utils import TestClient, TestServer

        from opsagent_tpu.serving.api import build_engine_app

        stack = ServingStack(Engine(EngineConfig(**BASE)))
        app = build_engine_app(stack)

        async def scenario():
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                jid = "chatcmpl-deadbeefdeadbeefdeadbeef"
                r = await client.post(
                    "/v1/chat/completions",
                    json={
                        "messages": [
                            {"role": "user", "content": "hdr hop"}
                        ],
                        "max_tokens": 4, "temperature": 0,
                    },
                    headers={
                        "X-Fleet-Request-Id": jid,
                        "X-Fleet-Hop": "route",
                        "X-Fleet-Replica": "r9",
                    },
                )
                assert r.status == 200, await r.text()
                body = await r.json()
                assert body["id"] == jid
            finally:
                await client.close()

        import asyncio

        try:
            asyncio.new_event_loop().run_until_complete(scenario())
        finally:
            stack.close()


# -- fleet flight ledger ------------------------------------------------------
class TestFleetFlight:
    def test_merged_ledger_is_replica_tagged_and_ordered(self):
        router, stacks = _fleet(2)
        try:
            resp = router.complete({
                "messages": [{"role": "user", "content": "ledger"}],
                "max_tokens": 4, "temperature": 0,
            })
            led = router.fleet_flight(n=0)
            assert set(led["replicas"]) == {"r0", "r1"}
            assert led["events"], "process ring must contribute events"
            walls = [
                e.get("wall_corrected", e.get("wall", 0.0))
                for e in led["events"]
            ]
            assert walls == sorted(walls)
            assert all("source" in e for e in led["events"])
            # request_id filter narrows to the journey's events.
            only = router.fleet_flight(request_id=resp["id"])["events"]
            assert only
            assert all(e["request_id"] == resp["id"] for e in only)
        finally:
            _close(stacks)

    def test_anomaly_dump_carries_the_journey(self):
        router, stacks = _fleet(1)
        try:
            resp = router.complete({
                "messages": [{"role": "user", "content": "dump me"}],
                "max_tokens": 4, "temperature": 0,
            })
            ctx = obs.flight.get_recorder()._dump_context(
                {"request_id": resp["id"]}
            )
            legs = [
                c for c in ctx if c.get("kind") == "fleet_journey"
            ]
            assert legs, "anomaly context must include the journey"
            assert legs[0]["replicas"] == ["r0"]
            assert legs[0]["hops"]
        finally:
            _close(stacks)


# -- THE acceptance gate ------------------------------------------------------
def test_failover_plus_fault_in_yields_one_stitched_timeline():
    """Streamed request through a forced mid-SSE failover AND a
    pagestore peer fault-in: one stitched timeline from the router with
    segments from both replicas, router-side failover + fault-in
    windows, >= 95% coverage, monotonic non-overlapping segments,
    byte-identical output, zero post-warmup compiles."""
    # Reference: the same two turns on ONE replica, fault-free.
    ref_stack = ServingStack(Engine(EngineConfig(**BASE)))
    try:
        messages = [
            {"role": "system", "content": "journey test"},
            {"role": "user", "content": "first turn here"},
        ]
        r1 = ref_stack.chat_completion(
            {"messages": messages, "max_tokens": 8, "temperature": 0}
        )
        turn1_text = r1["choices"][0]["message"]["content"] or ""
        turn2_msgs = list(messages) + [
            {"role": "assistant", "content": turn1_text},
            {"role": "user", "content": "second turn now"},
        ]
        r2 = ref_stack.chat_completion(
            {"messages": turn2_msgs, "max_tokens": 12, "temperature": 0}
        )
        want_turn2 = r2["choices"][0]["message"]["content"] or ""
        assert want_turn2
    finally:
        ref_stack.close()

    router, stacks = _fleet(2)   # pagestore directory ON by default
    try:
        # Turn 1 pinned to r0: the chain's pages live on r0 and are
        # advertised through the directory.
        resp1 = router.complete(
            {"messages": messages, "max_tokens": 8, "temperature": 0},
            force_replica="r0",
        )
        assert (resp1["choices"][0]["message"]["content"] or "") == \
            turn1_text

        # Turn 2 streamed, unforced: affinity routes to r0, the 5th
        # chunk pull dies (injected), failover resumes on r1, whose
        # admission faults the chain in from r0 peer-to-peer.
        faults.configure("fleet.stream_disconnect@5")
        chunks = list(router.complete_stream({
            "messages": turn2_msgs, "max_tokens": 12, "temperature": 0,
            "stream": True,
        }))
        faults.reset()
        assert all("error" not in c for c in chunks), chunks
        text = "".join(
            c["choices"][0]["delta"].get("content") or ""
            for c in chunks
        )
        assert text == want_turn2          # byte-identical across the seam
        jid = chunks[0]["id"]

        rec = router.participants_of(jid)
        assert rec is not None and rec["shape"] == "failover"
        assert set(rec["replicas"]) >= {"r0", "r1"}

        # The pagestore fault-in ran as part of THIS journey.
        fi = [
            e for e in obs.flight.get_recorder().snapshot(
                kind="page_fault_in"
            )
            if e.get("request_id") == jid
        ]
        assert any(
            e.get("phase") == "exit" and e.get("pages", 0) > 0
            for e in fi
        ), fi

        # ONE stitched timeline from the router.
        tl = router.timeline(jid)
        assert tl is not None and tl.get("fleet") is True
        assert tl["shape"] == "failover"
        lanes_with_segments = {
            s["replica"] for s in tl["segments"]
        }
        assert len(lanes_with_segments) >= 2, tl["segments"]
        kinds = {w["kind"] for w in tl["windows"]}
        assert "failover" in kinds, kinds
        assert "fault_in" in kinds, kinds
        assert tl["coverage"] >= 0.95, (tl["coverage"], tl["windows"])
        for prev, cur in zip(tl["segments"], tl["segments"][1:]):
            assert cur["start_ms"] >= prev["end_ms"] - 1e-6, (prev, cur)
        # The journey counted once under its most eventful shape.
        assert sum(
            obs.FLEET_JOURNEYS.value(**{"shape": "failover", "class": c})
            for c in obs.SLO_CLASSES
        ) >= 1
        # Renderable as a multi-lane gantt with both replica lanes.
        art = obs_timeline.render_fleet_gantt(tl)
        assert "lane r0:" in art and "lane r1:" in art
        assert "fault_in" in art
        # Zero-post-warmup-compiles invariant held throughout.
        compiles = [
            e for e in obs.flight.get_recorder().snapshot(kind="anomaly")
            if e.get("reason") == "post_warmup_compile"
        ]
        assert not compiles
    finally:
        faults.reset()
        _close(stacks)


def test_stitched_timeline_degrades_when_participant_is_reaped():
    router, stacks = _fleet(2)
    try:
        faults.configure("fleet.stream_disconnect@5")
        chunks = list(router.complete_stream({
            "messages": [{"role": "user", "content": "reap test"}],
            "max_tokens": 12, "temperature": 0, "stream": True,
        }))
        faults.reset()
        jid = chunks[0]["id"]
        rec = router.participants_of(jid)
        assert rec and len(rec["replicas"]) == 2
        # In-process replicas share the trace store, so reaping one
        # still leaves the shared source: the stitch must survive and
        # stay fleet-shaped rather than 404 or raise.
        dead = rec["replicas"][0]
        router.registry.deregister(dead)
        tl = router.timeline(jid)
        assert tl is not None and tl.get("fleet") is True
        assert tl["segments"]
    finally:
        faults.reset()
        _close(stacks)
