"""Goodput-ledger timeline tests: phase assembly over trace spans +
flight events (non-overlapping, gap-free coverage), engine-restart
stitching (events from both engine generations land in one timeline),
the full agent-e2e acceptance gate (>= 95 % wall-clock coverage with an
exactly-bounded tool-blocked window), the Gantt renderer, and the
endpoint round trips (incl. the agent server's JWT guard on
/api/timeline and /api/debug/memory)."""

import time

import jax.numpy as jnp
import pytest

from opsagent_tpu import obs
from opsagent_tpu.obs import timeline
from opsagent_tpu.obs.timeline import assemble, render_gantt


def _assert_phases_partition(tl, max_gap_frac=0.05):
    """Phases must be sorted, non-overlapping, and cover nearly the
    whole request (gaps only below the sweep threshold)."""
    phases = tl["phases"]
    assert phases
    cursor = None
    for seg in phases:
        assert seg["end_ms"] >= seg["start_ms"]
        if cursor is not None:
            assert seg["start_ms"] >= cursor - 1e-6, (
                f"overlap at {seg}"
            )
        cursor = seg["end_ms"]
    assert tl["goodput"]["coverage"] >= 1.0 - max_gap_frac


def test_assembly_from_synthetic_trace_and_events():
    rid = obs.new_request_id("tl")
    t = obs.Trace(rid)
    obs.get_store().add(t)
    base = t.root.t0
    gen = t.root.start_child("llm_turn")
    g = gen.start_child("generate")
    g.child("queue_wait", base + 0.001, base + 0.011)
    g.child("prefill", base + 0.011, base + 0.061, prompt_tokens=20)
    dec = g.start_child("decode")
    dec.t0 = base + 0.061
    dec.close(tokens=30)
    dec.t1 = base + 0.161
    g.close()
    gen.close()
    # A tool window bounded by the flight enter/exit pair.
    rec = obs.flight.get_recorder()
    e1 = rec.record("tool_exec", phase="enter", tool="kubectl",
                    request_id=rid)
    e2 = rec.record("tool_exec", phase="exit", tool="kubectl",
                    outcome="ok", duration_ms=40.0, request_id=rid)
    e1["ts"] = base + 0.170
    e2["ts"] = base + 0.210
    t.root.t1 = base + 0.215
    t.finished = True

    tl = assemble(rid)
    assert tl is not None
    _assert_phases_partition(tl)
    names = [p["phase"] for p in tl["phases"]]
    for expected in ("queued", "prefill", "decode_active", "tool_blocked"):
        assert expected in names, names
    g = tl["goodput"]
    assert abs(g["decode_active"] - 100.0 / 215.0) < 0.02
    assert abs(g["tool_blocked"] - 40.0 / 215.0) < 0.02
    # Fractions partition the wall clock.
    assert abs(sum(
        g[p] for p in ("decode_active", "tool_blocked", "queued",
                       "prefill", "host")
    ) - g["coverage"]) < 0.01


def test_assembly_survives_engine_restart_mid_request():
    """A restart re-admits the request under a NEW seq_id with the same
    request ID: both generations' events must stitch into one timeline,
    with engine_generations = restarts + 1 and both prefill/decode
    passes segmented."""
    rid = obs.new_request_id("tl")
    t = obs.Trace(rid)
    obs.get_store().add(t)
    base = t.root.t0
    gen = t.root.start_child("generate")
    # Generation 1: admitted as seq 7, decoded a while, then the engine
    # died.
    gen.child("queue_wait", base + 0.000, base + 0.005)
    gen.child("prefill", base + 0.005, base + 0.045)
    gen.child("decode", base + 0.045, base + 0.100, tokens=10)
    # Generation 2 (re-admission after restart): new seq id 31.
    gen.child("queue_wait", base + 0.130, base + 0.135)
    gen.child("prefill", base + 0.135, base + 0.160)
    gen.child("decode", base + 0.160, base + 0.240, tokens=12)
    gen.close()
    t.root.t1 = base + 0.245
    t.finished = True

    rec = obs.flight.get_recorder()
    stamps = {}

    def ev(kind, dt, **kw):
        e = rec.record(kind, **kw)
        e["ts"] = base + dt
        stamps[kind + str(kw.get("seq_id", ""))] = e
        return e

    ev("admission", 0.005, seq_id=7, prompt_tokens=20,
       prefix_hit_tokens=0, request_id=rid)
    ev("dispatch", 0.020, op="prefill_chunk", seq_id=7, prefill_tokens=20)
    ev("ttft", 0.045, seq_id=7, ttft_ms=40.0, request_id=rid)
    ev("anomaly", 0.110, reason="engine_restart", restart=1,
       max_restarts=3, running=1, prefilling=0)
    ev("admission", 0.135, seq_id=31, prompt_tokens=30,
       prefix_hit_tokens=0, request_id=rid)
    ev("ttft", 0.160, seq_id=31, ttft_ms=25.0, request_id=rid)
    ev("finish", 0.240, seq_id=31, tokens=12, finish_reason="stop",
       request_id=rid)

    tl = assemble(rid)
    assert tl is not None
    assert tl["engine_restarts"] == 1
    assert tl["engine_generations"] == 2
    assert tl["seq_ids"] == [7, 31]
    _assert_phases_partition(tl)
    # Both generations' prefill+decode passes are present.
    assert [p["phase"] for p in tl["phases"]].count("prefill") == 2
    assert [p["phase"] for p in tl["phases"]].count("decode_active") == 2
    kinds = [e["kind"] for e in tl["events"]]
    assert kinds.count("admission") == 2
    assert "anomaly" in kinds  # the restart itself is in the story
    # Dispatch events attribute through the seq set even without a
    # request_id of their own.
    assert any(e["kind"] == "dispatch" for e in tl["events"])


def test_assembly_from_flight_events_alone():
    """Trace evicted (ring of 512): coarse phases still come from the
    admission/ttft/finish events."""
    rid = obs.new_request_id("tl")
    rec = obs.flight.get_recorder()
    base = time.perf_counter()
    for kind, dt, kw in (
        ("admission", 0.0, dict(seq_id=3, prompt_tokens=8, request_id=rid)),
        ("ttft", 0.030, dict(seq_id=3, ttft_ms=30.0, request_id=rid)),
        ("finish", 0.100, dict(seq_id=3, tokens=9, finish_reason="stop",
                               request_id=rid)),
    ):
        e = rec.record(kind, **kw)
        e["ts"] = base + dt
    tl = assemble(rid)
    assert tl is not None
    names = [p["phase"] for p in tl["phases"]]
    assert "prefill" in names and "decode_active" in names


def test_assemble_unknown_request_returns_none():
    assert assemble("req-does-not-exist") is None


def test_render_gantt_is_ascii_and_scaled():
    tl = {
        "request_id": "req-x",
        "duration_ms": 100.0,
        "engine_generations": 2,
        "goodput": {"decode_active": 0.5, "tool_blocked": 0.3,
                    "queued": 0.0, "prefill": 0.1, "host": 0.1,
                    "coverage": 1.0},
        "phases": [
            {"phase": "prefill", "start_ms": 0.0, "end_ms": 10.0,
             "duration_ms": 10.0},
            {"phase": "decode_active", "start_ms": 10.0, "end_ms": 60.0,
             "duration_ms": 50.0},
            {"phase": "tool_blocked", "start_ms": 60.0, "end_ms": 90.0,
             "duration_ms": 30.0, "attrs": {"tool": "kubectl"}},
        ],
    }
    out = render_gantt(tl, width=40)
    assert "req-x" in out and "2 engine generations" in out
    assert "tool=kubectl" in out
    lines = out.splitlines()
    dec = next(ln for ln in lines if ln.startswith("decode_active"))
    # The decode bar occupies roughly half the width.
    assert 15 <= dec.count("#") <= 25
    assert all(ord(c) < 128 for c in out)  # ASCII only


def test_agent_e2e_timeline_acceptance(fake_tools, monkeypatch):
    """The acceptance gate: a full agent request through the real
    serving stack (ReAct -> tpu:// provider -> scheduler -> engine ->
    FSM-constrained decode) yields a timeline whose phases cover >= 95 %
    of the request's wall clock with no overlaps, including a
    tool-blocked window bounded by the new tool enter/exit flight
    events. The engine path is fully real; only the which-tool DECISION
    is scripted (random tiny weights emit schema-valid ToolPrompts whose
    action.name is data-dependent), so the tool subprocess window is
    guaranteed to exist."""
    from opsagent_tpu.agent import react
    from opsagent_tpu.agent.react import assistant_with_config
    from opsagent_tpu.serving.api import ServingStack, install_stack, _stacks
    from opsagent_tpu.serving.engine import Engine, EngineConfig
    from opsagent_tpu.tools import ToolAction, ToolPrompt

    calls = {"n": 0}

    class ScriptedParse:
        """ToolPrompt stand-in whose from_json scripts the agent's
        decisions: first reply -> call kubectl, second -> final answer."""

        @staticmethod
        def from_json(text):
            calls["n"] += 1
            if calls["n"] == 1:
                return ToolPrompt(
                    thought="check the cluster",
                    action=ToolAction(name="kubectl", input="get ns"),
                )
            return ToolPrompt(
                observation="3 namespaces",
                final_answer="There are 3 namespaces in the cluster.",
            )

    monkeypatch.setattr(react, "ToolPrompt", ScriptedParse)

    def kubectl(inp: str) -> str:
        time.sleep(0.12)  # the tool-subprocess window
        return "namespace-a namespace-b namespace-c"

    fake_tools({"kubectl": kubectl})

    cfg = EngineConfig(
        model="tiny-test", dtype=jnp.float32, tp=1, page_size=8,
        num_pages=256, max_pages_per_seq=128, max_batch_size=2,
        prefill_buckets=(256, 512, 1024), max_new_tokens_default=32,
    )
    s = ServingStack(Engine(cfg))
    install_stack("tl-agent", s)
    try:
        rid = obs.new_request_id("e2e")
        messages = [
            {"role": "system", "content": "you are a test agent"},
            {"role": "user", "content": "count namespaces"},
        ]
        with obs.trace_request(rid):
            out, history = assistant_with_config(
                "tpu://tl-agent", messages, max_tokens=32, max_iterations=3
            )
        # The loop returns the model's RAW final reply; the scripted
        # parse drove it through exactly one tool call then a final
        # answer, so the history holds two engine turns.
        assert calls["n"] == 2
        assert sum(1 for m in history if m["role"] == "assistant") == 2

        tl = assemble(rid)
        assert tl is not None
        # >= 95 % coverage, no overlapping phases.
        _assert_phases_partition(tl, max_gap_frac=0.05)
        names = [p["phase"] for p in tl["phases"]]
        for expected in ("queued", "prefill", "decode_active",
                         "tool_blocked"):
            assert expected in names, names

        # The tool window is bounded by the enter/exit event pair, and
        # the timeline's tool_blocked segment agrees with it.
        evs = [e for e in tl["events"] if e["kind"] == "tool_exec"]
        enters = [e for e in evs if e.get("phase") == "enter"]
        exits = [e for e in evs if e.get("phase") == "exit"]
        assert len(enters) == 1 and len(exits) == 1
        assert exits[0]["outcome"] == "ok"
        assert exits[0]["duration_ms"] >= 120.0
        assert exits[0]["request_id"] == rid
        window = exits[0]["t_ms"] - enters[0]["t_ms"]
        tool_segs = [p for p in tl["phases"] if p["phase"] == "tool_blocked"]
        assert abs(sum(p["duration_ms"] for p in tool_segs) - window) < 25.0
        assert tl["goodput"]["tool_blocked"] > 0.0

        # The goodput counters saw the same story.
        from opsagent_tpu.obs import attribution

        assert attribution.GOODPUT_SECONDS.value(phase="tool_blocked") >= 0.12
        assert attribution.GOODPUT_SECONDS.value(phase="decode_active") > 0
        assert attribution.GOODPUT_SECONDS.value(phase="prefill") > 0

        # /metrics carries the bytes-per-step split generated by the run.
        text = obs.metrics_text()
        assert 'opsagent_attr_bytes_total{kind="weights"}' in text
        assert 'opsagent_attr_bytes_total{kind="kv_read"}' in text

        # The Gantt renders the same timeline.
        g = render_gantt(tl)
        assert "tool_blocked" in g and "tool=kubectl" in g
    finally:
        s.close()
        _stacks.pop("tl-agent", None)


def test_timeline_endpoint_on_engine_server():
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from opsagent_tpu.serving.api import build_engine_app

    class _FakeStack:
        model_name = "tiny-test"
        engine = None

    rid = obs.new_request_id("tl")
    t = obs.Trace(rid)
    obs.get_store().add(t)
    t.root.start_child("prefill").close()
    t.root.close()
    t.finished = True

    app = build_engine_app(_FakeStack())

    async def scenario():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.get(f"/api/timeline/{rid}")
            assert r.status == 200
            body = await r.json()
            assert body["request_id"] == rid
            assert body["phases"]
            r = await client.get("/api/timeline/req-nope")
            assert r.status == 404
            # Memory profile: 403 without an operator-configured dir.
            r = await client.get("/api/debug/memory")
            assert r.status == 403
        finally:
            await client.close()

    asyncio.get_event_loop_policy().new_event_loop().run_until_complete(
        scenario()
    )


def test_agent_server_timeline_and_memory_jwt_guarded(monkeypatch, tmp_path):
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from opsagent_tpu.server.app import build_app
    from opsagent_tpu.server.jwtauth import issue_token
    from opsagent_tpu.utils.globalstore import set_global

    set_global("jwtKey", "test-key")
    monkeypatch.setenv("OPSAGENT_PROFILE_DIR", str(tmp_path))
    rid = obs.new_request_id("tl")
    t = obs.Trace(rid)
    obs.get_store().add(t)
    t.root.start_child("prefill").close()
    t.root.close()
    t.finished = True

    async def scenario():
        client = TestClient(TestServer(build_app()))
        await client.start_server()
        try:
            r = await client.get(f"/api/timeline/{rid}")
            assert r.status == 401  # JWT-guarded
            token = issue_token("admin", "test-key")
            hdr = {"Authorization": f"Bearer {token}"}
            r = await client.get(f"/api/timeline/{rid}", headers=hdr)
            assert r.status == 200
            assert (await r.json())["request_id"] == rid

            r = await client.get("/api/debug/memory")
            assert r.status == 401  # JWT-guarded
            r = await client.get("/api/debug/memory", headers=hdr)
            # jax on CPU still writes a (host) memory profile.
            assert r.status == 200, await r.text()
            body = await r.json()
            assert body["path"].startswith(str(tmp_path))
            import os

            assert os.path.exists(body["path"])
        finally:
            await client.close()

    asyncio.get_event_loop_policy().new_event_loop().run_until_complete(
        scenario()
    )


def test_anomaly_dump_is_self_contained(monkeypatch, tmp_path):
    """A TTFT-breach dump carries the attribution snapshot and the
    triggering request's timeline — a postmortem needs no live process."""
    import json

    monkeypatch.setenv("OPSAGENT_FLIGHT_DIR", str(tmp_path))
    rid = obs.new_request_id("tl")
    t = obs.Trace(rid)
    obs.get_store().add(t)
    t.root.start_child("prefill").close()
    t.root.close()
    t.finished = True
    rec = obs.flight.get_recorder()
    rec.record("admission", seq_id=1, prompt_tokens=4, request_id=rid)
    path = rec.anomaly("ttft_breach", seq_id=1, ttft_ms=900.0,
                       threshold_ms=500.0, request_id=rid)
    assert path is not None
    lines = [json.loads(ln) for ln in open(path)]
    kinds = [ln["kind"] for ln in lines]
    assert "attribution_snapshot" in kinds
    assert "timeline" in kinds
    tl_line = next(ln for ln in lines if ln["kind"] == "timeline")
    assert tl_line["request_id"] == rid
    assert "events" not in tl_line  # the ring itself is already the dump
    attr_line = next(
        ln for ln in lines if ln["kind"] == "attribution_snapshot"
    )
    assert "bytes_by_kind" in attr_line
