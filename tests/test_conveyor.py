"""Conveyor tool overlap tests (agent/conveyor.py + the react rewire).

Covers the full ladder: the split-anywhere streaming JSON parser, the
launch-readiness registry vs each tool module's declaration, the shared
subprocess helper's group-kill discipline, the async ToolLaunch executor
(incl. the tool.exec/tool.timeout fault points), TurnConveyor's
launch-at-readiness + flight accounting, the ReAct-loop integration
(transcript byte-equality on vs off, chaos fallback, mismatch-cancel),
park-at-launch page accounting against a real offload-tier engine, the
timeline's decode/tool overlap windows, and the trained-agent e2e whose
gantt must show the tool window hidden behind decode.
"""

import json
import os
import subprocess
import sys
import time

import pytest

from opsagent_tpu import obs
from opsagent_tpu import tools as tools_pkg
from opsagent_tpu.agent import conveyor, react
from opsagent_tpu.agent.conveyor import (
    StreamParser,
    ToolLaunch,
    TurnConveyor,
    _call_path,
)
from opsagent_tpu.serving import faults
from opsagent_tpu.serving.constrained import TOOLPROMPT_SCHEMA
from opsagent_tpu.tools import (
    LAUNCH_READY,
    ToolError,
    launch_ready_fields,
    proc,
    wire_fields_for,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def tp_json(name="kubectl", tool_input="kubectl get ns",
            observation="", final=""):
    """A wire-order ToolPrompt reply like the constrained decode emits."""
    return json.dumps({
        "question": "how many namespaces?",
        "thought": "count them",
        "action": {"name": name, "input": tool_input},
        "observation": observation,
        "final_answer": final,
    })


def tool_events(events):
    return [e for e in events if e.get("kind") == "tool_exec"]


# -- streaming parser --------------------------------------------------------


def test_call_path_of_toolprompt_schema():
    assert _call_path(TOOLPROMPT_SCHEMA) == ("action",)
    assert _call_path(None) == ("action",)
    assert _call_path({"properties": {"invoke": {
        "type": "object", "properties": {"name": {}, "input": {}},
    }}}) == ("invoke",)


def test_parser_event_order_char_by_char():
    p = StreamParser(TOOLPROMPT_SCHEMA)
    events = []
    for ch in tp_json():
        events.extend(p.feed(ch))
    kinds = [(e.kind, e.field) for e in events]
    # Wire order: question, thought, then the action object (name closes
    # first, input closes second, then the object itself), then the tail.
    assert kinds == [
        ("field_closed", "question"),
        ("field_closed", "thought"),
        ("tool_name_closed", "name"),
        ("arg_closed", "input"),
        ("field_closed", ""),          # the action OBJECT closed
        ("field_closed", "observation"),
        ("field_closed", "final_answer"),
        ("call_closed", ""),
    ]
    by_kind = {e.kind: e for e in events}
    assert by_kind["tool_name_closed"].value == "kubectl"
    assert by_kind["arg_closed"].value == "kubectl get ns"
    assert by_kind["arg_closed"].path == ("action", "input")


def test_parser_chunking_invariant():
    """Any split of the stream yields the same events (a token's
    detokenization can split escapes and keys arbitrarily)."""
    text = tp_json(tool_input='get pods -o jsonpath="{.items}" \\ tail')
    whole = StreamParser(TOOLPROMPT_SCHEMA).feed(text)
    for n in (1, 2, 3, 7, 64):
        p = StreamParser(TOOLPROMPT_SCHEMA)
        chunked = []
        for i in range(0, len(text), n):
            chunked.extend(p.feed(text[i:i + n]))
        assert [(e.kind, e.field, e.value) for e in chunked] == \
            [(e.kind, e.field, e.value) for e in whole], f"chunk={n}"
    args = [e.value for e in whole if e.kind == "arg_closed"]
    assert args == ['get pods -o jsonpath="{.items}" \\ tail']


def test_parser_escaped_quote_split_across_deltas():
    p = StreamParser(TOOLPROMPT_SCHEMA)
    text = tp_json(tool_input='echo "hi"')  # wire form carries \" escapes
    cut = text.index('\\"') + 1  # split BETWEEN backslash and quote
    events = p.feed(text[:cut]) + p.feed(text[cut:])
    args = [e.value for e in events if e.kind == "arg_closed"]
    assert args == ['echo "hi"']


def test_parser_non_string_scalars_and_nesting():
    p = StreamParser({"properties": {"call": {
        "type": "object", "properties": {"name": {}},
    }}})
    events = p.feed(
        '{"n": 42, "ok": true, "call": {"name": "jq", "depth": 3},'
        ' "arr": [1, 2]}'
    )
    vals = {(e.kind, e.field): e.value for e in events}
    assert vals[("field_closed", "n")] == 42
    assert vals[("field_closed", "ok")] is True
    assert vals[("tool_name_closed", "name")] == "jq"
    assert vals[("arg_closed", "depth")] == 3
    assert events[-1].kind == "call_closed"


def test_parser_ignores_bytes_after_root_close():
    p = StreamParser(TOOLPROMPT_SCHEMA)
    events = p.feed(tp_json())
    assert events[-1].kind == "call_closed"
    assert p.feed('{"question": "again"}') == []


# -- launch-readiness registry ----------------------------------------------


def test_launch_ready_matches_tool_module_declarations():
    """The central registry and each tool module's own LAUNCH_FIELDS
    must agree — a drifted declaration would launch on the wrong field."""
    from opsagent_tpu.tools import jq, kubectl, python_tool, trivy

    mods = {
        "kubectl": kubectl, "python": python_tool,
        "trivy": trivy, "jq": jq,
    }
    for name, mod in mods.items():
        assert LAUNCH_READY[name] == mod.LAUNCH_FIELDS, name
        assert launch_ready_fields(name) == LAUNCH_READY[name]
    # The agent wire format carries ONE "input" string per call, so every
    # tool is stream-launchable the moment that field closes.
    for name in list(LAUNCH_READY) + ["unknown-tool"]:
        assert wire_fields_for(name) == frozenset({"input"})
    assert launch_ready_fields("unknown-tool") == ("input",)


# -- shared subprocess helper ------------------------------------------------


def test_proc_run_matches_subprocess_contract():
    r = proc.run([sys.executable, "-c", "print('out'); "
                  "import sys; print('err', file=sys.stderr)"])
    assert r.returncode == 0
    assert r.stdout.strip() == "out"
    assert r.stderr.strip() == "err"


def test_proc_run_pipes_input_text():
    r = proc.run([sys.executable, "-c",
                  "import sys; print(sys.stdin.read().upper())"],
                 input_text="hello")
    assert r.stdout.strip() == "HELLO"


def test_proc_timeout_kills_whole_group():
    """A timed-out child's DESCENDANTS die too (the old subprocess.run
    path leaked `bash -c` grandchildren past the timeout)."""
    t0 = time.perf_counter()
    p = proc.ToolProcess(
        ["bash", "-c", "sleep 30 & echo started; wait"], timeout=0.3,
    )
    with pytest.raises(subprocess.TimeoutExpired):
        p.result()
    assert time.perf_counter() - t0 < 10.0
    assert p.timed_out
    # The group (bash + its backgrounded sleep) is gone.
    deadline = time.monotonic() + 3.0
    while time.monotonic() < deadline:
        try:
            os.killpg(p.proc.pid, 0)
        except ProcessLookupError:
            break
        time.sleep(0.05)
    else:
        pytest.fail("process group survived the timeout kill")


def test_proc_cancel_scope_registers_spawns():
    procs = []
    with proc.cancel_scope(procs):
        p = proc.ToolProcess(["sleep", "30"])
    assert procs == [p]
    p.cancel()
    assert p.wait(5.0)
    assert p.cancelled


# -- async ToolLaunch executor ----------------------------------------------


def test_tool_launch_result_and_matches():
    launch = ToolLaunch("kubectl", "get ns", lambda s: f"ran:{s}")
    assert launch.result() == "ran:get ns"
    assert launch.done() and launch.t_done is not None
    assert launch.matches("kubectl", "get ns")
    assert not launch.matches("kubectl", "get pods")
    assert not launch.matches("jq", "get ns")


def test_tool_launch_delivers_errors():
    def boom(s):
        raise ToolError("kubectl not reachable")

    launch = ToolLaunch("kubectl", "get ns", boom)
    launch.wait(5.0)
    assert isinstance(launch.error(), ToolError)
    with pytest.raises(ToolError, match="not reachable"):
        launch.result()


def test_tool_launch_fault_points_fire_in_worker():
    """tool.exec / tool.timeout inject into the ASYNC executor — the
    same chaos surface the classic blocking path has."""
    faults.configure("tool.exec@1")
    try:
        launch = ToolLaunch("kubectl", "get ns", lambda s: "ok")
        with pytest.raises(ToolError, match="injected tool subprocess"):
            launch.result()
    finally:
        faults.reset()
    faults.configure("tool.timeout@1")
    try:
        launch = ToolLaunch("kubectl", "get ns", lambda s: "ok")
        with pytest.raises(TimeoutError):
            launch.result()
    finally:
        faults.reset()


def test_tool_launch_cancel_reaps_subprocess():
    """cancel() group-kills a subprocess the worker spawned via
    tools/proc.py even though the canceller never held its handle."""
    def slow_tool(s):
        return proc.run(["sleep", "30"]).stdout

    launch = ToolLaunch("python", "irrelevant", slow_tool)
    deadline = time.monotonic() + 3.0
    while not launch._procs and time.monotonic() < deadline:
        time.sleep(0.01)
    assert launch._procs, "worker never spawned its subprocess"
    launch.cancel()
    assert launch.wait(10.0), "cancelled worker did not unwind"
    assert launch.cancelled


# -- TurnConveyor ------------------------------------------------------------


def test_turn_conveyor_launches_before_call_closes():
    ran = []

    def kubectl(s):
        ran.append(s)
        return "3 namespaces"

    turn = TurnConveyor({"kubectl": kubectl},
                        schema=TOOLPROMPT_SCHEMA)
    text = tp_json()
    cut = text.index('"observation"')
    turn.on_delta(text[:cut])
    # The input field closed -> the bet is already in flight, while the
    # observation/final_answer tail is still "decoding".
    assert turn.launch is not None
    assert turn.launch.name == "kubectl"
    assert turn.launch.input == "kubectl get ns"
    assert obs.TOOL_EARLY_LAUNCHES.value(tool="kubectl") == 1.0
    turn.on_delta(text[cut:])
    turn.finish_stream()
    assert turn.launch.result() == "3 namespaces"
    assert ran == ["kubectl get ns"]
    assert turn.overlap_s() >= 0.0
    enter = tool_events(obs.flight.get_recorder().snapshot())[0]
    assert enter["phase"] == "enter"
    assert enter["conveyor"] is True
    assert enter["launch_offset_ms"] >= 0.0
    assert "opsagent_tool_launch_lead_seconds" in obs.metrics_text()


def test_turn_conveyor_ignores_unknown_tool_and_missing_fields():
    turn = TurnConveyor({"kubectl": lambda s: "ok"},
                        schema=TOOLPROMPT_SCHEMA)
    turn.on_delta(tp_json(name="not-a-tool"))
    assert turn.launch is None
    assert obs.TOOL_EARLY_LAUNCHES.value(tool="not-a-tool") == 0.0
    # Name closed but input still streaming: no launch yet.
    turn2 = TurnConveyor({"kubectl": lambda s: "ok"},
                         schema=TOOLPROMPT_SCHEMA)
    text = tp_json()
    turn2.on_delta(text[:text.index('"input"')])
    assert turn2.launch is None


def test_turn_conveyor_abort_records_cancelled_exit():
    turn = TurnConveyor({"kubectl": lambda s: "ok"},
                        schema=TOOLPROMPT_SCHEMA)
    text = tp_json()
    turn.on_delta(text[:text.index('"observation"')])
    assert turn.launch is not None
    turn.abort()
    evs = tool_events(obs.flight.get_recorder().snapshot())
    assert [e["phase"] for e in evs] == ["enter", "exit"]
    assert evs[1]["outcome"] == "cancelled"
    assert evs[1]["conveyor"] is True


# -- ReAct loop integration --------------------------------------------------


def _scripted_replies(tool_input="kubectl get ns"):
    return [
        tp_json(tool_input=tool_input),
        tp_json(name="", tool_input="", observation="3",
                final="There are 3 namespaces in the cluster."),
    ]


def _fake_stream(replies, chunk=7, delay_s=0.003):
    """Stand-in for conveyor.stream_constrained_turn: feeds each scripted
    reply through on_delta in small paced chunks so a launched tool gets
    a real decode tail to overlap with."""
    it = iter(replies)

    def fake(model, max_tokens, messages, response_format, on_delta):
        text = next(it)
        for i in range(0, len(text), chunk):
            on_delta(text[i:i + chunk])
            time.sleep(delay_s)
        return text

    return fake


def _fake_chat(replies):
    it = iter(replies)

    def fake(self, model, max_tokens, messages, **kw):
        return next(it)

    return fake


def _run_react(monkeypatch, fake_tools, replies, conveyor_on, tool,
               model="tpu://convey-test"):
    monkeypatch.setenv("OPSAGENT_CONVEYOR", "1" if conveyor_on else "0")
    fake_tools({"kubectl": tool})
    if conveyor_on:
        monkeypatch.setattr(
            conveyor, "stream_constrained_turn", _fake_stream(replies)
        )
    else:
        from opsagent_tpu.llm.client import ChatClient

        monkeypatch.setattr(ChatClient, "chat", _fake_chat(replies))
    messages = [
        {"role": "system", "content": "you are a test agent"},
        {"role": "user", "content": "count namespaces"},
    ]
    return react.assistant_with_config(
        model, messages, 256, False, False, 4, "", ""
    )


def test_react_conveyor_transcripts_identical_on_vs_off(
    monkeypatch, fake_tools
):
    """The tentpole's correctness bar: the conveyor changes WHEN the tool
    runs, never what the agent says — transcripts are byte-identical."""
    def kubectl(s):
        time.sleep(0.12)
        return "namespace-a\nnamespace-b\nnamespace-c"

    out_on, hist_on = _run_react(
        monkeypatch, fake_tools, _scripted_replies(), True, kubectl
    )
    early = obs.TOOL_EARLY_LAUNCHES.value(tool="kubectl")
    overlap = obs.TOOL_OVERLAP_SECONDS.value()
    assert early == 1.0
    assert overlap > 0.0
    evs = tool_events(obs.flight.get_recorder().snapshot())
    assert [e["phase"] for e in evs] == ["enter", "exit"]
    assert evs[0]["conveyor"] is True and "launch_offset_ms" in evs[0]
    assert evs[1]["outcome"] == "ok"
    assert evs[1]["overlap_ms"] > 0.0

    out_off, hist_off = _run_react(
        monkeypatch, fake_tools, _scripted_replies(), False, kubectl
    )
    # No new early launches in the off phase.
    assert obs.TOOL_EARLY_LAUNCHES.value(tool="kubectl") == early
    assert obs.TOOL_OVERLAP_SECONDS.value() == overlap
    assert out_on == out_off
    assert [(m["role"], m["content"]) for m in hist_on] == \
        [(m["role"], m["content"]) for m in hist_off]
    assert "3 namespaces" in json.loads(out_on)["final_answer"]


def test_react_conveyor_chaos_falls_back_to_classic(
    monkeypatch, fake_tools
):
    """tool.exec fault mid-overlap: the early launch dies, the classic
    relaunch completes the turn, the transcript is unchanged — zero lost
    tokens, both flight pairs on record."""
    calls = []

    def kubectl(s):
        calls.append(s)
        return "namespace-a\nnamespace-b\nnamespace-c"

    base_out, base_hist = _run_react(
        monkeypatch, fake_tools, _scripted_replies(), True, kubectl
    )
    assert calls == ["kubectl get ns"]
    obs.flight.get_recorder().reset()

    calls.clear()
    faults.configure("tool.exec@1")  # hit 1 = the conveyor worker
    try:
        out, hist = _run_react(
            monkeypatch, fake_tools, _scripted_replies(), True, kubectl
        )
    finally:
        faults.reset()
    # The worker died before reaching the tool; the classic path ran it.
    assert calls == ["kubectl get ns"]
    assert out == base_out
    assert [(m["role"], m["content"]) for m in hist] == \
        [(m["role"], m["content"]) for m in base_hist]
    evs = tool_events(obs.flight.get_recorder().snapshot())
    assert [(e["phase"], e.get("conveyor", False)) for e in evs] == [
        ("enter", True), ("exit", True),   # the failed early launch
        ("enter", False), ("exit", False),  # the classic relaunch
    ]
    assert evs[1]["outcome"] == "error"
    assert "injected tool subprocess" in evs[1]["error"]
    assert evs[3]["outcome"] == "ok"
    assert obs.TOOL_CALLS.value(tool="kubectl", outcome="error") == 1.0
    assert obs.TOOL_CALLS.value(tool="kubectl", outcome="ok") >= 1.0


def test_react_conveyor_mismatch_cancels_and_reruns(
    monkeypatch, fake_tools
):
    """Launched prefix != final parse: the bet is cancelled and the
    classic path runs the PARSED call; the flight ring records both."""
    from opsagent_tpu.tools import ToolPrompt

    class Tampering:
        """Parse wrapper that diverges from the streamed prefix."""

        @staticmethod
        def from_json(text):
            tp = ToolPrompt.from_json(text)
            if tp.action.name:
                tp.action.input = tp.action.input + " --tampered"
            return tp

    monkeypatch.setattr(react, "ToolPrompt", Tampering)
    calls = []

    def kubectl(s):
        time.sleep(0.05)
        calls.append(s)
        return "namespace-a"

    out, hist = _run_react(
        monkeypatch, fake_tools, _scripted_replies(), True, kubectl
    )
    # Both flights ran: the cancelled speculative input and the parsed one.
    assert sorted(calls) == [
        "kubectl get ns", "kubectl get ns --tampered",
    ]
    evs = tool_events(obs.flight.get_recorder().snapshot())
    assert [(e["phase"], e.get("conveyor", False)) for e in evs] == [
        ("enter", True), ("exit", True),
        ("enter", False), ("exit", False),
    ]
    assert evs[1]["outcome"] == "cancelled"
    assert evs[3]["outcome"] == "ok"
    assert "3 namespaces" in json.loads(out)["final_answer"]


def test_react_conveyor_aborts_on_final_answer_turn(
    monkeypatch, fake_tools
):
    """A reply that dispatches no tool leaves no dangling launch."""
    replies = [tp_json(name="", tool_input="", observation="seen",
                       final="Nothing to do here, all healthy.")]
    out, hist = _run_react(
        monkeypatch, fake_tools, replies, True, lambda s: "never"
    )
    assert json.loads(out)["final_answer"].startswith("Nothing")
    assert obs.TOOL_EARLY_LAUNCHES.value(tool="kubectl") == 0.0
    assert tool_events(obs.flight.get_recorder().snapshot()) == []


# -- park-at-launch accounting ----------------------------------------------


@pytest.mark.slow
def test_park_at_launch_frees_pages_for_readmission():
    """The launch parks the session's KV to the host tier: parked_tokens
    must match the allocator's page delta exactly, and the freed HBM must
    be re-admittable while the tool overlaps the decode tail."""
    import jax.numpy as jnp

    from opsagent_tpu.serving import api as serving_api
    from opsagent_tpu.serving.engine import Engine, EngineConfig

    eng = Engine(EngineConfig(
        model="tiny-test", dtype=jnp.float32, tp=1, page_size=8,
        num_pages=128, max_pages_per_seq=32, max_batch_size=2,
        prefill_buckets=(128, 256), max_new_tokens_default=8,
        offload=True,
    ))
    stack = serving_api.ServingStack(eng)
    serving_api.install_stack("convey-park", stack)
    try:
        messages = [
            {"role": "system", "content": "you are a cluster assistant "
                                          "counting namespaces"},
            {"role": "user", "content": "how many namespaces does the "
                                        "cluster have right now?"},
        ]
        # Populate the prefix trie with this session's chain.
        stack.chat_completion({
            "model": "convey-park", "messages": messages,
            "max_tokens": 8, "temperature": 0.0,
        })
        acct0 = eng.alloc.accounting()
        turn = TurnConveyor(
            {"kubectl": lambda s: "3"}, model="tpu://convey-park",
            park_messages=messages, schema=TOOLPROMPT_SCHEMA,
        )
        text = tp_json()
        turn.on_delta(text[:text.index('"observation"')])
        assert turn.launch is not None
        acct1 = eng.alloc.accounting()
        pages_freed = acct1["free"] - acct0["free"]
        assert turn.parked_tokens > 0
        assert turn.parked_tokens == pages_freed * eng.cfg.page_size
        enter = tool_events(obs.flight.get_recorder().snapshot())[0]
        assert enter["parked_tokens"] == turn.parked_tokens
        # The freed pages are usable DURING the overlap window.
        r = stack.chat_completion({
            "model": "convey-park",
            "messages": [{"role": "user", "content": "another session "
                          "admitted while the tool overlaps decode"}],
            "max_tokens": 8, "temperature": 0.0,
        })
        assert r["choices"][0]["message"]["content"] is not None
        turn.on_delta(text[text.index('"observation"'):])
        turn.finish_stream()
        assert turn.launch.result() == "3"
        turn.record_exit("ok", overlap_s=turn.overlap_s())
        evs = tool_events(obs.flight.get_recorder().snapshot())
        assert evs[-1]["outcome"] == "ok"
        assert evs[-1]["parked_tokens"] == turn.parked_tokens
    finally:
        serving_api.uninstall_stack("convey-park")
        stack.close()


# -- timeline overlap windows ------------------------------------------------


def test_timeline_overlap_windows_and_gantt():
    """assemble() intersects conveyor tool windows with decode_active and
    reports the hidden time; render_gantt adds a tool_overlap row."""
    from opsagent_tpu.obs.timeline import assemble, render_gantt

    rec = obs.flight.get_recorder()
    rid = "req-convey-tl"
    base = time.perf_counter()
    for kind, dt, kw in (
        ("admission", 0.0, dict(seq_id=991, prompt_tokens=8)),
        ("ttft", 0.030, dict(seq_id=991, ttft_ms=30.0)),
        ("tool_exec", 0.040, dict(tool="kubectl", phase="enter",
                                  conveyor=True, launch_offset_ms=12.0)),
        ("tool_exec", 0.070, dict(tool="kubectl", phase="exit",
                                  outcome="ok", duration_ms=30.0,
                                  conveyor=True)),
        ("finish", 0.100, dict(seq_id=991, tokens=9,
                               finish_reason="stop")),
    ):
        e = rec.record(kind, request_id=rid, **kw)
        e["ts"] = base + dt
    tl = assemble(rid)
    assert tl is not None
    # Tool 40..70 ms entirely inside decode 30..100 ms.
    assert abs(tl["tool_overlap_ms"] - 30.0) < 1.0
    w = tl["overlap_windows"][0]
    assert w["tool"] == "kubectl"
    assert abs(w["start_ms"] - 40.0) < 1.0
    assert abs(w["end_ms"] - 70.0) < 1.0
    assert abs(w["duration_ms"] - 30.0) < 1.0
    g = render_gantt(tl)
    assert "tool_overlap" in g
    assert "tool=kubectl" in g
    assert "tool overlap hidden behind decode" in g


def test_timeline_classic_tool_window_has_no_overlap_rows():
    """Non-conveyor tool windows (the blocking path) must not count as
    overlap: the decode was NOT running under them."""
    from opsagent_tpu.obs.timeline import assemble, render_gantt

    rec = obs.flight.get_recorder()
    rid = "req-classic-tl"
    base = time.perf_counter()
    for kind, dt, kw in (
        ("admission", 0.0, dict(seq_id=992, prompt_tokens=8)),
        ("ttft", 0.010, dict(seq_id=992, ttft_ms=10.0)),
        ("tool_exec", 0.020, dict(tool="kubectl", phase="enter")),
        ("tool_exec", 0.040, dict(tool="kubectl", phase="exit",
                                  outcome="ok", duration_ms=20.0)),
        ("finish", 0.050, dict(seq_id=992, tokens=4,
                               finish_reason="stop")),
    ):
        e = rec.record(kind, request_id=rid, **kw)
        e["ts"] = base + dt
    tl = assemble(rid)
    assert tl is not None
    assert tl["tool_overlap_ms"] == 0.0
    assert tl["overlap_windows"] == []
    assert "tool_overlap" not in render_gantt(tl)


# -- trained-agent e2e -------------------------------------------------------


@pytest.mark.slow
def test_trained_agent_e2e_gantt_shows_overlap(tmp_path, monkeypatch):
    """The acceptance gate end to end on real machinery: train the tiny
    agent to memorization, serve it, run the episode with the conveyor
    on — the launch must fire mid-decode, the transcript must match the
    conveyor-off run byte for byte, and the timeline gantt must show the
    tool window overlapping the decode span."""
    import jax.numpy as jnp

    from opsagent_tpu.agent.react import assistant_with_config
    from opsagent_tpu.obs.timeline import assemble, render_gantt
    from opsagent_tpu.serving import api as serving_api
    from opsagent_tpu.serving.engine import Engine, EngineConfig
    from opsagent_tpu.tools.replay import (
        NAMESPACES_SCRIPT,
        install_replay_kubectl,
    )

    scripts = os.path.join(REPO, "scripts")
    sys.path.insert(0, scripts)
    try:
        from train_tiny_agent import (
            INSTRUCTION,
            SYS_PROMPT,
            train_checkpoint,
        )
    finally:
        sys.path.remove(scripts)

    ckpt, tok_path, cfg, loss, _ = train_checkpoint(str(tmp_path))
    assert loss < 0.05, f"tiny agent failed to memorize: loss={loss}"

    monkeypatch.setenv("PATH", os.environ["PATH"])
    install_replay_kubectl(NAMESPACES_SCRIPT, str(tmp_path / "bin"))
    real_kubectl = tools_pkg.get_tools()["kubectl"]

    def paced_kubectl(arg):
        time.sleep(0.15)  # a real execution window to hide
        return real_kubectl(arg)

    monkeypatch.setitem(tools_pkg.copilot_tools, "kubectl", paced_kubectl)

    eng = Engine(
        EngineConfig(
            model="tiny-test", checkpoint=ckpt, tokenizer=tok_path,
            dtype=jnp.float32, num_pages=512, page_size=16,
            max_pages_per_seq=64, max_batch_size=2,
            prefill_buckets=(128, 512, 1024),
        ),
        model_cfg=cfg,
    )
    stack = serving_api.ServingStack(eng)
    serving_api.install_stack("convey-e2e", stack)
    messages = [
        {"role": "system", "content": SYS_PROMPT},
        {"role": "user",
         "content": f"Here are the instructions: {INSTRUCTION}"},
    ]
    try:
        results = {}
        for tag in ("on", "off"):
            monkeypatch.setenv(
                "OPSAGENT_CONVEYOR", "1" if tag == "on" else "0"
            )
            rid = obs.new_request_id("convey")
            with obs.trace_request(rid):
                out, hist = assistant_with_config(
                    "tpu://convey-e2e", [dict(m) for m in messages],
                    256, False, False, 4, "", "",
                )
            results[tag] = (rid, out,
                            [(m["role"], m["content"]) for m in hist])
        assert results["on"][1] == results["off"][1]
        assert results["on"][2] == results["off"][2]
        assert obs.TOOL_EARLY_LAUNCHES.value(tool="kubectl") >= 1.0
        assert obs.TOOL_OVERLAP_SECONDS.value() > 0.0

        tl = assemble(results["on"][0])
        assert tl is not None
        assert tl["tool_overlap_ms"] > 0.0, tl["phases"]
        assert tl["overlap_windows"][0]["tool"] == "kubectl"
        g = render_gantt(tl)
        assert "tool_overlap" in g and "tool=kubectl" in g

        tl_off = assemble(results["off"][0])
        assert tl_off is not None
        assert tl_off["tool_overlap_ms"] == 0.0
    finally:
        serving_api.uninstall_stack("convey-e2e")
        stack.close()
