"""Training-step tests on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp

from opsagent_tpu.models import llama
from opsagent_tpu.models.config import get_config_preset
from opsagent_tpu.parallel.mesh import make_mesh
from opsagent_tpu.training import (
    TrainConfig,
    cross_entropy_loss,
    init_train_state,
    make_train_step,
)


def test_cross_entropy_matches_manual():
    logits = jnp.asarray(
        [[[2.0, 0.0, -1.0], [0.5, 0.5, 0.5]]], jnp.float32
    )  # [1, 2, 3]
    targets = jnp.asarray([[0, 2]], jnp.int32)
    mask = jnp.asarray([[1.0, 0.0]])  # only the first position counts
    got = cross_entropy_loss(logits, targets, mask)
    logz = jax.nn.logsumexp(logits[0, 0])
    want = float(logz - logits[0, 0, 0])
    assert abs(float(got) - want) < 1e-5


def test_train_step_overfits_tiny_batch():
    cfg = get_config_preset("tiny-test")
    tc = TrainConfig(learning_rate=3e-3, remat=False)
    mesh = make_mesh(tp=2, dp=2, sp=2)
    params, opt_state = init_train_state(
        cfg, tc, mesh, jax.random.PRNGKey(0), dtype=jnp.float32
    )
    step = make_train_step(cfg, tc, mesh, dtype=jnp.float32)
    tokens = jnp.asarray(
        jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab_size),
        jnp.int32,
    )
    mask = jnp.ones((4, 32), jnp.float32)
    losses = []
    for _ in range(5):
        params, opt_state, metrics = step(params, opt_state, tokens, mask)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], losses
    assert all(l == l for l in losses)  # no NaN


def test_remat_matches_no_remat():
    cfg = get_config_preset("tiny-test")
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    tokens = jnp.asarray(
        jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size),
        jnp.int32,
    )
    a = llama.forward_full(params, cfg, tokens, dtype=jnp.float32, remat=False)
    b = llama.forward_full(params, cfg, tokens, dtype=jnp.float32, remat=True)
    assert jnp.allclose(a, b, atol=1e-5)


def test_graft_entry_single_chip():
    import __graft_entry__ as g

    fn, args = g.entry()
    logits = jax.jit(fn)(*args)
    assert logits.shape[0] == 2 and logits.ndim == 3


def test_graft_dryrun_multichip_8():
    import __graft_entry__ as g

    g.dryrun_multichip(8)


def test_mesh_has_pp_axis_and_distributed_noop():
    """SURVEY §2.2: the mesh names every parallelism axis (pp/dp/sp/ep/tp)
    so adding a strategy is an annotation change, not a mesh redesign; and
    init_distributed is a no-op single-host."""
    from opsagent_tpu.parallel.mesh import init_distributed, make_mesh

    mesh = make_mesh(tp=2, dp=2, sp=2)
    assert dict(mesh.shape) == {
        "pp": 1, "dp": 2, "sp": 2, "ep": 1, "tp": 2
    }
    mesh2 = make_mesh(tp=1, dp=1, sp=1, pp=2, devices=jax.devices()[:2])
    assert mesh2.shape["pp"] == 2
    assert init_distributed() == 1  # no coordinator env: single host
