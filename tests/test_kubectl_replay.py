"""Record/replay kubectl transcripts through the REAL subprocess tool path.

VERDICT round-1 weak #9: the tool layer was only tested with in-process
python doubles, so tools/kubectl.py's ``bash -c`` execution, kubectl
prepending, pipe handling, and noise filter had never run against a real
binary boundary. Here a replay `kubectl` executable on PATH serves recorded
transcripts (command -> output), asserting the exact commands the agent
issues — the Python answer to the record/replay fixtures the reference
never had (its kubectl tests did not exist at all; SURVEY §4)."""

import json
import os
import stat
import textwrap

import pytest

from opsagent_tpu.agent.react import assistant_with_config
from opsagent_tpu.tools import ToolError, get_tools
from opsagent_tpu.tools.kubectl import kubectl


TRANSCRIPT = [
    {
        "args": "get ns --no-headers",
        "out": "default Active 10d\nkube-system Active 10d\n"
               "kube-public Active 10d\n",
        "rc": 0,
    },
    {
        "args": "get pods -n default --no-headers",
        "out": (
            "E0307 12:34:56.789012 1 memcache.go:287] "
            "couldn't get current server API group list\n"
            "web-1 1/1 Running 0 3d\n"
            "web-2 0/1 CrashLoopBackOff 12 3d\n"
        ),
        "rc": 0,
    },
    {
        "args": "get pods -n missing",
        "out": "Error from server (NotFound): namespaces \"missing\" not found\n",
        "rc": 1,
    },
]


@pytest.fixture
def replay_kubectl(tmp_path, monkeypatch):
    """Install a `kubectl` executable that replays TRANSCRIPT in order and
    records every invocation; yields the path of the invocation log."""
    transcript_file = tmp_path / "transcript.json"
    transcript_file.write_text(json.dumps(TRANSCRIPT))
    calls_file = tmp_path / "calls.jsonl"
    cursor_file = tmp_path / "cursor"
    cursor_file.write_text("0")
    script = tmp_path / "kubectl"
    script.write_text(textwrap.dedent(f"""\
        #!/usr/bin/env python3
        import json, sys
        args = " ".join(sys.argv[1:])
        with open({str(transcript_file)!r}) as f:
            transcript = json.load(f)
        with open({str(cursor_file)!r}) as f:
            i = int(f.read().strip())
        with open({str(calls_file)!r}, "a") as f:
            f.write(json.dumps(args) + "\\n")
        if i >= len(transcript):
            sys.stderr.write(f"replay exhausted at call {{i}}: {{args}}\\n")
            sys.exit(97)
        entry = transcript[i]
        with open({str(cursor_file)!r}, "w") as f:
            f.write(str(i + 1))
        if entry["args"] != args:
            sys.stderr.write(
                f"replay mismatch at call {{i}}: expected "
                f"{{entry['args']!r}}, got {{args!r}}\\n")
            sys.exit(98)
        sys.stdout.write(entry["out"])
        sys.exit(entry["rc"])
    """))
    script.chmod(script.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("PATH", f"{tmp_path}{os.pathsep}{os.environ['PATH']}")
    return calls_file


def test_pipes_and_prepend_through_real_shell(replay_kubectl):
    # No "kubectl" prefix and a shell pipe: the tool must prepend and the
    # pipe must run in the real shell against the replay binary's stdout.
    out = kubectl("get ns --no-headers | wc -l")
    assert out.strip() == "3"
    assert json.loads(replay_kubectl.read_text().splitlines()[0]) == (
        "get ns --no-headers"
    )


def test_noise_filter_on_real_output(replay_kubectl):
    kubectl("get ns --no-headers")  # consume entry 0
    out = kubectl("kubectl get pods -n default --no-headers")
    assert "E0307" not in out
    assert "couldn't get current server API group list" not in out
    assert "web-1" in out and "CrashLoopBackOff" in out


def test_nonzero_exit_raises_tool_error(replay_kubectl):
    kubectl("get ns --no-headers")
    kubectl("get pods -n default --no-headers")
    with pytest.raises(ToolError, match="NotFound"):
        kubectl("get pods -n missing")


def test_react_loop_end_to_end_over_replay(replay_kubectl, scripted_llm):
    """The full ladder: ReAct agent -> registry kubectl tool -> bash -c ->
    replay binary -> observation -> final answer. The transcript pins the
    exact command sequence the agent issued."""
    def tp(thought="", name="", input="", observation="", final=""):
        return json.dumps({
            "question": "q", "thought": thought,
            "action": {"name": name, "input": input},
            "observation": observation, "final_answer": final,
        })

    scripted_llm([
        tp(thought="list", name="kubectl", input="kubectl get ns --no-headers"),
        tp(thought="pods", name="kubectl",
           input="kubectl get pods -n default --no-headers"),
        tp(observation="3 namespaces; web-2 crashlooping",
           final="3 namespaces; pod web-2 is in CrashLoopBackOff."),
    ])
    assert get_tools()["kubectl"] is kubectl  # REAL registry entry, no double
    out, history = assistant_with_config(
        "fake://m",
        [{"role": "user", "content": "check the cluster"}],
        max_tokens=2048, count_tokens=False, verbose=False, max_iterations=5,
    )
    assert "CrashLoopBackOff" in out
    calls = [json.loads(l) for l in replay_kubectl.read_text().splitlines()]
    assert calls == [
        "get ns --no-headers",
        "get pods -n default --no-headers",
    ]
    # Observations really flowed back from the replay binary.
    fed = " ".join(
        m["content"] for m in history if m.get("role") == "user"
    )
    assert "kube-system" in fed and "web-2" in fed
